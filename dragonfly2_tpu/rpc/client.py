"""Typed RPC clients for the cluster edge.

Capability parity with pkg/rpc clients (pkg/rpc/scheduler/client/
client_v2.go GetV2/GetV2ByAddr typed surface, retry/backoff interceptors in
pkg/rpc/interceptor.go) and pkg/balancer's consistent-hashing policy
(consistent_hashing.go:40-57): a peer picks its scheduler by hashing the
task id onto the scheduler ring, so every RPC for one task lands on the
same scheduler — here via utils/hashring + a per-address connection pool.
"""

from __future__ import annotations

import asyncio
import logging
import threading
import typing

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.rpc import mux, resilience, wire
from dragonfly2_tpu.telemetry.tracing import default_tracer
from dragonfly2_tpu.utils import dferrors
from dragonfly2_tpu.utils.hashring import HashRing

wire.register_module(msg)

logger = logging.getLogger(__name__)


async def _bounded_wait(awaitable: typing.Awaitable[typing.Any],
                        timeout: float | None, what: str,
                        metrics: typing.Any = None) -> typing.Any:
    """await with the caller's timeout bounded by the ambient deadline
    budget (rpc/resilience.py). A timeout that was BUDGET-bound surfaces
    as DeadlineExceeded (and counts in the deadline family), a plain
    per-call timeout stays asyncio.TimeoutError — callers distinguish
    'the budget ran out' from 'this one call was slow'."""
    effective = resilience.bound_timeout(timeout)
    if effective is not None and effective <= 0:
        if metrics is not None:
            metrics.deadline_exceeded.labels().inc()
        raise dferrors.DeadlineExceeded(f"{what}: deadline budget exhausted")
    try:
        return await asyncio.wait_for(awaitable, effective)
    except asyncio.TimeoutError:
        if effective is not None and (timeout is None or effective < timeout):
            if metrics is not None:
                metrics.deadline_exceeded.labels().inc()
            raise dferrors.DeadlineExceeded(
                f"{what}: deadline budget exhausted after {effective:.3f}s"
            ) from None
        raise


class SchedulerConnection:
    """One long-lived announce stream to a scheduler (AnnouncePeer
    semantics: requests flow up, scheduling responses flow back async)."""

    def __init__(self, host: str, port: int, ssl_context: typing.Any = None,
                 resilience_metrics: typing.Any = None):
        self.host = host
        self.port = port
        self.ssl_context = ssl_context  # ssl.SSLContext for mTLS, None = plaintext
        # resilience_series namespace for the deadline_exceeded counter
        # (the pool passes its board's; a bare connection counts nothing)
        self._res_metrics = resilience_metrics
        self._reader: asyncio.StreamReader | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._responses: dict[str, asyncio.Queue] = {}
        self._stats: asyncio.Queue = asyncio.Queue()
        self._probe_targets: asyncio.Queue = asyncio.Queue()
        self._health: asyncio.Queue = asyncio.Queue()
        self.seed_triggers: asyncio.Queue = asyncio.Queue()
        self._reader_task: asyncio.Task | None = None
        self._send_lock = asyncio.Lock()
        # set by the daemon once AnnounceHost was sent ON THIS connection
        # (announced-ness cannot outlive the connection: a restarted
        # scheduler has fresh state)
        self.announced = False

    @property
    def is_closed(self) -> bool:
        """True once the transport is gone (peer restart, network cut) —
        the pool uses this to evict dead cached connections and redial,
        the behavior the reference gets from gRPC channel reconnects."""
        if self._writer is None or self._reader is None:
            return False  # never connected; nothing to evict
        return self._writer.is_closing() or self._reader.at_eof()

    async def connect(self) -> "SchedulerConnection":
        from dragonfly2_tpu.utils import vsock as vsock_mod

        if vsock_mod.is_vsock(self.host):
            # vsock://<cid> host + port -> AF_VSOCK dial (pkg/rpc/vsock.go
            # VsockDialer; the client_v1.go WithContextDialer path). The
            # ssl_context rides along — TLS clusters stay TLS over vsock.
            self._reader, self._writer = await vsock_mod.open_connection(
                f"{self.host}:{self.port}", ssl_context=self.ssl_context
            )
        else:
            self._reader, self._writer = await asyncio.open_connection(
                self.host, self.port, ssl=self.ssl_context
            )
        self._enable_tcp_keepalive()
        self._reader_task = asyncio.create_task(self._read_loop())
        return self

    def _enable_tcp_keepalive(self) -> None:
        """Kernel keepalives (~60 s to declare death) so a SILENT network
        cut — no FIN/RST: power loss, stateful firewall drop — surfaces
        as EOF on the read loop and flips `is_closed`. A mostly-idle seed
        connection would otherwise stay half-open forever and never learn
        its scheduler died (grpc's keepalive pings play this role for the
        reference)."""
        import socket as _socket

        sock = self._writer.get_extra_info("socket") if self._writer else None
        if sock is None:
            return
        try:
            sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_KEEPALIVE, 1)
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_KEEPIDLE, 30)
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_KEEPINTVL, 10)
            sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_KEEPCNT, 3)
        except (OSError, AttributeError):
            pass  # non-TCP transports (vsock) / platforms without the knobs

    async def close(self) -> None:
        if self._reader_task:
            self._reader_task.cancel()
            try:
                await self._reader_task
            except asyncio.CancelledError:
                pass
        if self._writer:
            self._writer.close()

    async def _read_loop(self) -> None:
        assert self._reader is not None
        while True:
            response = await wire.read_frame(self._reader)
            if response is None:
                # connection died: wake every waiter with the failure
                for q in self._responses.values():
                    q.put_nowait(
                        msg.ScheduleFailure(peer_id="", code="Unavailable", description="stream closed")
                    )
                return
            if isinstance(response, msg.StatResponse):
                self._stats.put_nowait(response)
            elif isinstance(response, mux.HealthCheckResponse):
                self._health.put_nowait(response)
            elif isinstance(response, msg.ProbeTargetsResponse):
                self._probe_targets.put_nowait(response)
            elif isinstance(response, msg.TriggerSeedRequest):
                self.seed_triggers.put_nowait(response)
            else:
                peer_id = getattr(response, "peer_id", "")
                q = self._responses.get(peer_id)
                if q is not None:
                    q.put_nowait(response)
                else:
                    logger.debug("dropping response for unknown peer %s", peer_id)

    async def send(self, request: typing.Any) -> None:
        assert self._writer is not None
        async with self._send_lock:
            wire.write_frame(self._writer, request)
            await self._writer.drain()

    def subscribe(self, peer_id: str) -> asyncio.Queue:
        return self._responses.setdefault(peer_id, asyncio.Queue())

    def unsubscribe(self, peer_id: str) -> None:
        self._responses.pop(peer_id, None)

    # ---------------------------------------------------- request/response
    # Per-call deadline enforcement: the caller's own timeout is bounded by
    # the ambient deadline budget (rpc/resilience.py), and the request
    # frame carries the remaining budget for the server's shed check.

    def _check(self, what: str) -> None:
        try:
            resilience.check(what)
        except dferrors.DeadlineExceeded:
            if self._res_metrics is not None:
                self._res_metrics.deadline_exceeded.labels().inc()
            raise

    async def stat_peer(self, peer_id: str, timeout: float = 5.0) -> msg.StatResponse:
        self._check("stat_peer")
        await self.send(msg.StatPeerRequest(peer_id=peer_id))
        return await _bounded_wait(self._stats.get(), timeout, "stat_peer",
                                   metrics=self._res_metrics)

    async def stat_task(self, task_id: str, timeout: float = 5.0) -> msg.StatResponse:
        self._check("stat_task")
        await self.send(msg.StatTaskRequest(task_id=task_id))
        return await _bounded_wait(self._stats.get(), timeout, "stat_task",
                                   metrics=self._res_metrics)

    async def sync_probes(
        self, host_id: str, count: int = 10, timeout: float = 5.0
    ) -> list[msg.ProbeTarget]:
        self._check("sync_probes")
        await self.send(msg.ProbeStartedRequest(host_id=host_id, count=count))
        response = await _bounded_wait(self._probe_targets.get(), timeout,
                                       "sync_probes", metrics=self._res_metrics)
        return response.targets

    async def health(self, timeout: float = 2.0) -> bool:
        """One HealthCheck round trip on the live stream (pkg/rpc/health) —
        the half-open breaker probe rides this instead of inventing a new
        message."""
        await self.send(mux.HealthCheckRequest(service="scheduler"))
        response = await _bounded_wait(self._health.get(), timeout, "health",
                                       metrics=self._res_metrics)
        return response.status == mux.SERVING


class SchedulerClientPool:
    """Task-affine scheduler selection over a scheduler set (the
    consistent-hashing balancer + resolver pair)."""

    def __init__(self, addresses: list[tuple[str, int]],
                 ssl_context: typing.Any = None,
                 breaker_failure_threshold: int = 2,
                 breaker_open_ttl: float = 5.0):
        if not addresses:
            raise ValueError("need at least one scheduler address")
        self.ssl_context = ssl_context
        # (ring, addr) swap as ONE tuple: update_addresses runs on the
        # dynconfig worker thread while the event loop reads in for_task;
        # two separate assignments could pair a new ring with the old addr
        # map and KeyError on a just-added scheduler (ADVICE r3).
        self._state: tuple[HashRing, dict] = (
            HashRing([f"{h}:{p}" for h, p in addresses]),
            {f"{h}:{p}": (h, p) for h, p in addresses},
        )
        # Per-target dial breakers (rpc/resilience.py): a blackholed
        # scheduler costs `failure_threshold` dial timeouts, then every
        # later dial fast-fails until the open_ttl probe window.
        self.breakers = resilience.BreakerBoard(
            "dfdaemon", failure_threshold=breaker_failure_threshold,
            open_ttl=breaker_open_ttl,
        )
        self._conns: dict[str, SchedulerConnection] = {}
        # (connection, parked_at): closed by for_task only after a grace
        # period, so an RPC already in flight on a just-removed scheduler
        # finishes instead of dying mid-exchange. Guarded by _stale_mu
        # (a THREAD lock, held only across list ops, never an await):
        # the dynconfig worker thread appends while _get swaps, and an
        # unguarded append landing on the just-swapped-out list would
        # leak that connection unclosed forever (ADVICE r4 low).
        self._stale_conns: list[tuple[SchedulerConnection, float]] = []
        self._stale_mu = threading.Lock()
        self._lock = asyncio.Lock()

    STALE_CLOSE_GRACE_S = 30.0

    @property
    def _ring(self) -> HashRing:
        return self._state[0]

    @property
    def _addr(self) -> dict:
        return self._state[1]

    def update_addresses(self, addresses: list[tuple[str, int]]) -> None:
        """Dynconfig-driven refresh (pkg/resolver semantics). Thread-safe
        against the event loop: one atomic tuple swap; connections to
        removed schedulers are parked and closed on the loop by the next
        for_task (closing an asyncio transport from this worker thread
        would race the loop)."""
        addr = {f"{h}:{p}": (h, p) for h, p in addresses}
        self._state = (HashRing(list(addr)), addr)
        import time as _time

        for key in list(self._conns):
            if key not in addr:
                # dflint: waive[LOCK001] -- _lock is an asyncio.Lock owned by the event loop; this worker thread cannot await it. The pop is GIL-atomic; a conn _get resurrects concurrently is parked+closed by the next update sweep (docstring above)
                conn = self._conns.pop(key, None)
                if conn is not None:
                    with self._stale_mu:
                        self._stale_conns.append((conn, _time.monotonic()))
        # breakers follow ring membership: a decommissioned scheduler's
        # breaker must not linger as a stuck-open gauge
        for target in self.breakers.targets():
            if target not in addr:
                self.breakers.drop(target)

    async def for_task(self, task_id: str) -> SchedulerConnection:
        """Live connection for a task: the hashring PRIMARY when it is
        healthy, else ring-order failover — breaker-open or dial-dead
        candidates are skipped and the task lands on the next ring node
        (where it would also land if the primary left the ring, so the
        failed-over task keeps scheduler affinity through the outage).
        The happy path pays one O(log n) pick; the full successor walk
        (nodes x replicas) is built only after the primary failed."""
        ring, addr = self._state
        primary = ring.pick(task_id)
        if primary is None:
            raise RuntimeError("scheduler ring is empty")
        try:
            # _get returns a live cached connection without consulting the
            # breaker (it guards DIALS, not established streams), so the
            # healthy-primary fast path costs one dict lookup
            return await self._get(primary, addr)
        except (resilience.BreakerOpen, OSError, asyncio.TimeoutError) as e:
            last_err: Exception = e
        failed = primary
        for key in ring.successors(task_id):
            if key == primary:
                continue
            logger.warning(
                "scheduler %s unavailable (%s); failing over to next "
                "ring node", failed, type(last_err).__name__,
            )
            try:
                return await self._get(key, addr)
            except (resilience.BreakerOpen, OSError, asyncio.TimeoutError) as e:
                last_err = e
                failed = key
                continue
        raise last_err

    def primary_for_task(self, task_id: str) -> str | None:
        """The hashring owner of `task_id` (chaos tests and operators ask
        'which scheduler should this task be on')."""
        return self._state[0].pick(task_id)

    def size(self) -> int:
        """Configured scheduler count (the ring membership, not how many
        connections happen to be open)."""
        return len(self._state[1])

    DIAL_TIMEOUT_S = 5.0

    async def for_address(self, host: str, port: int) -> SchedulerConnection:
        """Live connection to a SPECIFIC scheduler (seed loops are bound
        to the scheduler that owns them, not to a task hash). Raises
        LookupError when that scheduler has left the active set — callers
        must NOT resurrect schedulers dynconfig decommissioned."""
        key = f"{host}:{port}"
        _, addr = self._state
        if key not in addr:
            raise LookupError(f"scheduler {key} no longer in the active set")
        return await self._get(key, addr)

    async def _get(self, key: str, addr: dict) -> SchedulerConnection:
        async with self._lock:
            import time as _time

            now = _time.monotonic()
            # swap the list out under the thread lock: the dynconfig
            # worker appends concurrently, and an append racing the swap
            # would land on the dead list and leak its connection
            with self._stale_mu:
                pending, self._stale_conns = self._stale_conns, []
            for parked, at in pending:
                if now - at < self.STALE_CLOSE_GRACE_S:
                    with self._stale_mu:
                        self._stale_conns.append((parked, at))
                    continue
                try:
                    await parked.close()
                except Exception:  # noqa: BLE001 - best-effort teardown
                    pass
            conn = self._conns.get(key)
            if conn is not None and conn.is_closed:
                # scheduler restarted / connection died: evict and redial
                self._conns.pop(key, None)
                try:
                    await conn.close()
                except Exception:  # noqa: BLE001 - already dead
                    pass
                conn = None
            if conn is not None:
                return conn
        # Dial OUTSIDE the pool lock, bounded: one blackholed scheduler
        # (SYN drop after its connection died) must not stall every
        # download to the healthy ones behind this lock for the kernel's
        # multi-minute connect timeout. The dial runs under the target's
        # circuit breaker: an open breaker raises BreakerOpen in
        # microseconds instead of paying the timeout again, and the first
        # dial after open_ttl runs as the half-open probe — verified with
        # a HealthCheck round trip before the breaker closes.
        breaker_state = self.breakers.acquire(key)
        host, port = addr[key]
        fresh = SchedulerConnection(
            host, port, ssl_context=self.ssl_context,
            resilience_metrics=self.breakers.metrics,
        )
        try:
            await asyncio.wait_for(fresh.connect(), timeout=self.DIAL_TIMEOUT_S)
            if breaker_state == resilience.HALF_OPEN:
                if not await fresh.health():
                    raise ConnectionError(f"{key}: half-open probe NOT_SERVING")
        except BaseException as e:
            # Only a refusal/timeout is evidence against the TARGET; a
            # caller-side cancellation says nothing about its health and
            # must neither open the breaker nor wedge the half-open probe
            # slot (record_outcome classifies). Either way the half-open
            # socket must not leak (ADVICE r4 low).
            self.breakers.record_outcome(key, e)
            try:
                await fresh.close()
            except Exception:  # noqa: BLE001 - teardown of a dead dial
                pass
            raise
        self.breakers.record_outcome(key, None)
        async with self._lock:
            raced = self._conns.get(key)
            if raced is not None and not raced.is_closed:
                # another coroutine dialed while we were; keep one
                await fresh.close()
                return raced
            self._conns[key] = fresh
            return fresh

    def connections(self) -> list[SchedulerConnection]:
        return list(self._conns.values())

    async def connect_all(self) -> list[SchedulerConnection]:
        """Open a connection to every reachable scheduler (seed daemons
        must be reachable for triggers before any task touches them). Dead
        schedulers are skipped — the lazy per-task path retries them.
        Dials go through _get so they share the per-target breakers."""
        _, addr = self._state
        for key in list(addr):
            try:
                await self._get(key, addr)
            except (OSError, asyncio.TimeoutError, resilience.BreakerOpen) as e:
                logger.warning("scheduler %s unreachable: %s", key, e)
        async with self._lock:
            return list(self._conns.values())

    async def close(self) -> None:
        async with self._lock:
            for conn in self._conns.values():
                await conn.close()
            self._conns.clear()


class TrainerClient:
    """Client-streaming dataset upload (trainerv1.Trainer/Train)."""

    DIAL_TIMEOUT_S = 5.0

    def __init__(self, host: str, port: int, ssl_context: typing.Any = None):
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        # the upload runs on the scheduler's announce cadence: a blackholed
        # trainer must cost one bounded dial per open_ttl, not a full
        # kernel connect timeout per cadence tick
        self.breakers = resilience.BreakerBoard("scheduler")

    async def train(
        self, host_id: str, ip: str, hostname: str, datasets: dict,
        chunk_size: int = 128 << 20,
    ) -> msg.TrainResponse:
        """`datasets` maps name -> bytes OR an iterable of bytes parts
        (e.g. one per CSV rotation file), so callers can stream a large
        trace history without materializing it all at once."""
        with default_tracer().span(
            "scheduler.train_upload", host_id=host_id, datasets=len(datasets),
        ):
            return await self._train(host_id, ip, hostname, datasets, chunk_size)

    async def _train(
        self, host_id: str, ip: str, hostname: str, datasets: dict,
        chunk_size: int,
    ) -> msg.TrainResponse:
        # Every frame below inherits the upload span's context through the
        # wire envelope, so the trainer's train_ingest span continues this
        # trace (one trace id across the announce->train edge).
        target = f"{self.host}:{self.port}"
        breaker_state = self.breakers.acquire(target)
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(
                    self.host, self.port, ssl=self.ssl_context
                ),
                timeout=self.DIAL_TIMEOUT_S,
            )
            if breaker_state == resilience.HALF_OPEN:
                # probe the half-open breaker with the trainer's health
                # handler before streaming megabytes at a maybe-dead server
                try:
                    wire.write_frame(writer, mux.HealthCheckRequest(service="trainer"))
                    await writer.drain()
                    probe = await asyncio.wait_for(wire.read_frame(reader), timeout=2.0)
                    if not (
                        isinstance(probe, mux.HealthCheckResponse)
                        and probe.status == mux.SERVING
                    ):
                        raise ConnectionError(f"{target}: half-open probe NOT_SERVING")
                except BaseException:
                    # a failed/timed-out/cancelled probe must not leak the
                    # just-dialed socket (the fd-per-retry leak shape)
                    writer.close()
                    raise
        except BaseException as e:
            # record_outcome classifies: transport failure opens/advances
            # the breaker, cancellation just frees the probe slot
            self.breakers.record_outcome(target, e)
            raise
        self.breakers.record_outcome(target, None)
        try:
            try:
                for dataset, value in datasets.items():
                    parts = [value] if isinstance(value, (bytes, bytearray)) else value
                    sent_any = False
                    for blob in parts:
                        for off in range(0, max(len(blob), 1), chunk_size):
                            wire.write_frame(
                                writer,
                                msg.TrainRequest(
                                    host_id=host_id, ip=ip, hostname=hostname,
                                    dataset=dataset, chunk=blob[off : off + chunk_size],
                                ),
                            )
                            await writer.drain()
                            sent_any = True
                    if not sent_any:
                        wire.write_frame(
                            writer,
                            msg.TrainRequest(host_id=host_id, ip=ip, hostname=hostname,
                                             dataset=dataset, chunk=b""),
                        )
                        await writer.drain()
                # explicit commit marker: bare EOF means "torn", not "done"
                wire.write_frame(writer, msg.TrainEndRequest(host_id=host_id))
                await writer.drain()
                writer.write_eof()
            except (ConnectionError, RuntimeError):
                # The server may have replied with an error and closed its
                # read side mid-upload; fall through and try to collect that
                # response rather than losing it to the broken pipe.
                pass
            response = await wire.read_frame(reader)
            if not isinstance(response, msg.TrainResponse):
                return msg.TrainResponse(ok=False, description="bad trainer reply")
            return response
        finally:
            writer.close()


class SyncSchedulerClient:
    """Blocking request/response client over the scheduler wire protocol
    for NON-asyncio callers — the manager's REST worker threads driving
    the cross-process job edge (JobTriggerSeed / TaskStates /
    SchedulerInfo; the machinery hops the reference runs through Redis +
    asynq, manager/job + internal/job). One short-lived request at a time
    per client; the connection is dialed lazily and redialed after any
    error, so a scheduler restart costs one failed call, not a stuck
    manager."""

    def __init__(self, host: str, port: int, ssl_context: typing.Any = None,
                 timeout: float = 5.0, dial_failure_ttl: float = 5.0):
        self.host = host
        self.port = port
        self.ssl_context = ssl_context
        self.timeout = timeout
        # Per-target circuit breaker (rpc/resilience.py), generalizing the
        # old ad-hoc dial-failure TTL cache: a preheat fans one trigger per
        # task to the owning scheduler, and without it a dead (blackholed)
        # scheduler costs one full connect timeout PER TASK — minutes for a
        # 50-URL job. failure_threshold=1 keeps the old contract (one
        # failed dial → fast-fail), open_ttl=dial_failure_ttl keeps the
        # probe cadence, and the half-open probe now runs the health
        # request before the breaker closes.
        self.breakers = resilience.BreakerBoard(
            "manager", failure_threshold=1, open_ttl=dial_failure_ttl,
        )
        self._target = f"{host}:{port}"
        self._sock = None
        self._mu = threading.Lock()

    def _connect(self) -> typing.Any:
        import socket as _socket

        timeout = resilience.bound_timeout(self.timeout)
        sock = _socket.create_connection((self.host, self.port), timeout=timeout)
        if self.ssl_context is not None:
            sock = self.ssl_context.wrap_socket(sock, server_hostname=self.host)
        return sock

    def _dial(self) -> None:
        """Dial under the breaker; half-open dials are verified with one
        HealthCheck round trip before the breaker closes (pkg/rpc/health —
        the probe the reference's balancer gets from grpc healthchecks)."""
        breaker_state = self.breakers.acquire(self._target)  # BreakerOpen -> Unavailable
        try:
            self._sock = self._connect()
            if breaker_state == resilience.HALF_OPEN:
                self._sock.sendall(wire.encode(mux.HealthCheckRequest()))
                header = self._recv_exact(self._sock, 4)
                probe = wire.decode(
                    self._recv_exact(self._sock, int.from_bytes(header, "big"))
                )
                if not (
                    isinstance(probe, mux.HealthCheckResponse)
                    and probe.status == mux.SERVING
                ):
                    raise ConnectionError("half-open probe NOT_SERVING")
        except BaseException as e:
            # BaseException, not just (OSError, ConnectionError): a codec
            # error from a garbled probe reply (wire.decode TypeError)
            # must still settle the acquire — record_outcome classifies it
            # as release-not-failure — or the probe slot wedges and this
            # target becomes permanently unreachable
            self.breakers.record_outcome(self._target, e)
            sock, self._sock = self._sock, None
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            raise
        self.breakers.record_outcome(self._target, None)

    def call(self, request: typing.Any) -> typing.Any:
        """Send one frame, read one frame. Raises ConnectionError on any
        transport failure (after closing the cached socket), Unavailable
        when the breaker is open, DeadlineExceeded when the ambient budget
        is already spent. The socket is snapshotted into a local: a
        concurrent close() (update_schedulers dropping a departed
        scheduler) nulls self._sock without taking _mu — closing the fd
        mid-recv surfaces as OSError below, never as an AttributeError on
        None escaping the error mapping."""
        with self._mu:
            remaining = resilience.remaining()
            if remaining is not None and remaining <= 0:
                self.breakers.metrics.deadline_exceeded.labels().inc()
                raise dferrors.DeadlineExceeded(
                    f"scheduler rpc {self._target}: deadline budget exhausted"
                )
            try:
                if self._sock is None:
                    self._dial()
                sock = self._sock
                if remaining is not None:
                    # the recv timeout shrinks to the budget; the request
                    # frame itself carries the remaining budget (wire
                    # encode reads the ambient scope) for the server shed
                    sock.settimeout(min(self.timeout, remaining))
                # wire.encode already length-prefixes the frame
                sock.sendall(wire.encode(request))
                header = self._recv_exact(sock, 4)
                return wire.decode(
                    self._recv_exact(sock, int.from_bytes(header, "big"))
                )
            except resilience.BreakerOpen:
                raise  # already Unavailable with the open-state detail
            except (OSError, ConnectionError, ValueError) as e:
                self.close()
                raise ConnectionError(f"scheduler rpc {self.host}:{self.port}: {e}") from e
            finally:
                # snapshot, never re-read: a concurrent close() nulls
                # self._sock and an AttributeError out of a finally would
                # replace the in-flight exception and break the
                # ConnectionError contract this method documents
                sock = self._sock
                if sock is not None and remaining is not None:
                    try:
                        sock.settimeout(self.timeout)
                    except OSError:
                        pass

    def _recv_exact(self, sock: typing.Any, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("scheduler closed the connection")
            buf += chunk
        return buf

    def close(self) -> None:
        # snapshot-swap: two racing closers (a failing call()'s error path
        # and update_schedulers dropping the scheduler) must not leave one
        # of them calling close() on None
        # dflint: waive[LOCK001] -- deliberate lock-free snapshot-swap (GIL-atomic tuple assign); taking _mu here would deadlock a closer invoked from inside call()'s locked error path
        sock, self._sock = self._sock, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
