"""Async jobs: preheat fan-out and peer listing.

Capability parity with the machinery(Redis)-backed job layer: manager-side
CreatePreheat resolves content into tasks and fans group jobs out to
scheduler queues (manager/job/preheat.go:73-286); scheduler-side workers
consume `preheat` (seed-peer TriggerDownloadTask, scheduler/job/job.go:152)
and `sync_peers` (:224). Here the queue is in-proc (the gRPC/Redis edge can
wrap it); preheat enqueues a seed-download trigger (TriggerSeedRequest)
on the scheduler the hash ring assigns, which the RPC edge pushes to the
seed daemon's announce connection — the ObtainSeeds path, with the task
id derived exactly as the daemons derive it (idgen.task_id_v1).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import time
import uuid

from dragonfly2_tpu.cluster import image_preheat
from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.rpc import resilience
from dragonfly2_tpu.utils import dferrors, idgen
from dragonfly2_tpu.utils.hashring import HashRing


class JobState(str, enum.Enum):
    PENDING = "PENDING"
    SUCCESS = "SUCCESS"
    FAILURE = "FAILURE"
    # A task this job fanned out was observed alive earlier but is now
    # unknown to its scheduler (TTL GC, restart) without a latched terminal
    # outcome — the job's result is indeterminate, not forever-PENDING
    # (ADVICE r3: GC + an unpolled job used to pin PENDING permanently).
    EXPIRED = "EXPIRED"


@dataclasses.dataclass
class PreheatRequest:
    urls: list[str]
    tag: str = ""
    application: str = ""
    piece_length: int = 4 << 20
    filtered_query_params: list[str] | None = None
    # "file" fans the raw URLs out as-is; "image" resolves each URL as an
    # OCI image reference (registry manifest walk -> config+layer blob
    # URLs, manager/job/preheat.go:90-168) and preheats every blob. An
    # empty type sniffs: URLs matching .../v2/<repo>/manifests/<tag> are
    # treated as images.
    preheat_type: str = ""
    username: str = ""
    password: str = ""
    platform: str = ""
    headers: dict | None = None


@dataclasses.dataclass
class JobResult:
    job_id: str
    state: JobState
    task_ids: list[str]
    detail: dict = dataclasses.field(default_factory=dict)
    # monotonic enqueue (or adoption) time: bounds how long a preheat may
    # sit with NO task ever observed before it expires — the seed-trigger
    # delivery TTL is 60s, so a job whose tasks never appeared by then is
    # undeliverable (no seed daemon exists), not merely late
    created_at: float = dataclasses.field(default_factory=time.monotonic)


# How long a preheat may sit with no task ever observed before it is
# declared undeliverable — longer than the RPC drain's 60s trigger TTL
# (rpc/server.SEED_TRIGGER_TTL_S) so a late-but-delivered seed still wins.
SEED_START_TTL_S = 90.0


class RemoteScheduler:
    """The JobManager-facing surface of a scheduler in ANOTHER process,
    over the wire RPC job edge (rpc/server.py JobTriggerSeed/TaskStates/
    SchedulerInfo) — the role the reference's Redis-backed machinery bus
    plays between manager and scheduler processes (internal/job/
    job.go:53-87). Degrades per-call: an unreachable scheduler fails THIS
    trigger/poll, not the manager."""

    # Every job-edge op runs under a deadline scope (rpc/resilience.py):
    # the frame carries the remaining budget, so a scheduler that digs a
    # stale trigger/poll out of a backlog SHEDS it instead of doing work
    # the manager's REST thread stopped waiting for. One budget covers
    # dial + call.
    OP_BUDGET_S = 10.0

    def __init__(self, host: str, port: int, ssl_context=None):
        from dragonfly2_tpu.rpc.client import SyncSchedulerClient

        self.address = (host, port)
        self._client = SyncSchedulerClient(host, port, ssl_context=ssl_context)

    def trigger_seed_download(self, task_id, url, piece_length=4 << 20,
                              tag="", application="", host_id="",
                              headers=None) -> bool:
        try:
            with resilience.deadline(self.OP_BUDGET_S):
                resp = self._client.call(msg.JobTriggerSeedRequest(
                    task_id=task_id, url=url, piece_length=piece_length,
                    tag=tag, application=application, host_id=host_id,
                    headers=headers or {},
                ))
        except (ConnectionError, dferrors.DeadlineExceeded):
            return False
        return isinstance(resp, msg.JobTriggerSeedResponse) and resp.ok

    def task_states(self, task_ids: list[str]) -> list[int | None]:
        """None means 'this scheduler does not know the task' — a REAL
        answer. Transport failure RAISES ConnectionError instead: mapping
        it to None would read as 'scheduler forgot the task' and flip a
        healthy in-flight job to EXPIRED during a restart window."""
        with resilience.deadline(self.OP_BUDGET_S):
            resp = self._client.call(msg.TaskStatesRequest(task_ids=task_ids))
        if not isinstance(resp, msg.TaskStatesResponse):
            raise ConnectionError(f"bad TaskStates reply from {self.address}")
        return [None if s < 0 else s for s in resp.states]

    def info(self) -> tuple[dict, list]:
        """(counts, hosts) in ONE round trip — the response carries both.
        Raises ConnectionError when the scheduler is unreachable so
        callers can surface the failure instead of reporting a healthy
        empty scheduler."""
        with resilience.deadline(self.OP_BUDGET_S):
            resp = self._client.call(msg.SchedulerInfoRequest())
        if not isinstance(resp, msg.SchedulerInfoResponse):
            raise ConnectionError(f"bad SchedulerInfo reply from {self.address}")
        return resp.counts, resp.hosts

    def counts(self) -> dict:
        return self.info()[0]

    def list_hosts(self) -> list[dict]:
        return self.info()[1]

    def flight_recorder(self, last_n: int = 64) -> dict:
        """The remote scheduler's flight-recorder dump (last-N tick phase
        breakdowns + jit compile counters + open spans). Raises
        ConnectionError when unreachable so the manager surfaces the
        failure instead of an empty-but-healthy-looking dump."""
        with resilience.deadline(self.OP_BUDGET_S):
            resp = self._client.call(msg.FlightRecorderRequest(last_n=last_n))
        if not isinstance(resp, msg.FlightRecorderResponse):
            raise ConnectionError(f"bad FlightRecorder reply from {self.address}")
        return resp.dump

    def close(self) -> None:
        self._client.close()


class JobManager:
    """Routes jobs to schedulers by task-id consistent hashing — the same
    affinity the reference gets from pkg/balancer. Entries may be local
    SchedulerService objects (in-proc clusters, tests) or RemoteScheduler
    proxies (the launched manager's cross-process job edge)."""

    def __init__(self, schedulers: dict[str, SchedulerService],
                 seed_hosts: list[msg.HostInfo] | None = None):
        self.schedulers = schedulers
        self.ring = HashRing(list(schedulers))
        # Optional: with no explicit seed hosts, triggers go out with an
        # empty host_id and each SCHEDULER round-robins its own announced
        # seed hosts (SchedulerService.trigger_seed_download) — the
        # launched manager does not track per-scheduler seed daemons.
        self.seed_hosts = [h for h in (seed_hosts or [])]
        self._seed_rr = itertools.cycle(range(max(len(self.seed_hosts), 1)))
        self.jobs: dict[str, JobResult] = {}
        # per-job (task_done, task_seen) poll latches — PRIVATE bookkeeping,
        # deliberately not in JobResult.detail (the manager serializes
        # detail into the REST payload and DB record; these maps grow with
        # task count and are implementation state, not job output)
        self._latches: dict[str, tuple[dict, dict]] = {}

    def update_schedulers(self, schedulers: dict[str, SchedulerService]) -> None:
        """Swap the scheduler set (the launched manager refreshes it from
        its DB registrations before each job operation; schedulers come
        and go at runtime). Existing entries are kept by NAME so cached
        remote connections survive a no-op refresh."""
        merged = {
            name: self.schedulers.get(name, sched)
            for name, sched in schedulers.items()
        }
        for name, old in self.schedulers.items():
            if name not in merged and isinstance(old, RemoteScheduler):
                old.close()
        self.schedulers = merged
        self.ring = HashRing(list(merged))

    def adopt(self, job_id: str, task_ids: list[str]) -> JobResult:
        """Re-register a job known only from a durable record (the manager
        restarted; in-proc job state is documented non-durable). State
        recomputes from live task polling on the next get()."""
        result = self.jobs.get(job_id)
        if result is None:
            result = JobResult(job_id, JobState.PENDING, list(task_ids), {})
            self.jobs[job_id] = result
        return result

    def create_preheat(self, req: PreheatRequest) -> JobResult:
        """Resolve urls -> task ids and enqueue a TriggerSeedRequest per
        task on the owning scheduler, to be pushed to the chosen seed
        daemon's announce connection (preheat.go:90-286 + scheduler
        job.go:152-221). No peer is registered here — a peer registered
        on the seed's behalf would have no connection to receive
        responses, so nothing would download."""
        job_id = str(uuid.uuid4())
        task_ids = []
        failures = {}
        # Resolve the work list first: file preheats are the raw URLs;
        # image preheats walk the registry manifest into blob URLs
        # (preheat.go:99-117 CreatePreheat type dispatch).
        files: list[tuple[str, dict | None]] = []  # (url, headers)
        for url in req.urls:
            as_image = req.preheat_type == "image" or (
                not req.preheat_type and image_preheat.is_image_url(url)
            )
            if not as_image:
                files.append((url, req.headers))
                continue
            try:
                layers = image_preheat.resolve_image_layers(
                    url,
                    username=req.username,
                    password=req.password,
                    platform=req.platform,
                    headers=req.headers,
                )
            except Exception as e:  # noqa: BLE001 - fail THIS url, not the job run
                failures[url] = f"image resolve failed: {e}"
                continue
            files.extend((layer.url, layer.headers) for layer in layers)
        for url, headers in files:
            # v1 derivation, matching the daemons' dfget path
            # (client/daemon.py download -> idgen.task_id_v1): a preheat
            # that hashes differently from the peers seeds a task nobody
            # ever asks for.
            task_id = idgen.task_id_v1(
                url,
                tag=req.tag,
                application=req.application,
                filtered_query_params=idgen.FILTERED_QUERY_PARAMS_SEPARATOR.join(
                    req.filtered_query_params or []
                ),
            )
            task_ids.append(task_id)
            scheduler_name = self.ring.pick(task_id)
            if scheduler_name is None:
                failures[task_id] = "no scheduler"
                continue
            # explicit seed list -> manager round-robin; empty -> each
            # scheduler picks among ITS announced seed daemons
            seed_host_id = ""
            if self.seed_hosts:
                seed = self.seed_hosts[next(self._seed_rr) % len(self.seed_hosts)]
                seed_host_id = seed.host_id
            # .get, not []: a concurrent update_schedulers (manager REST
            # threads) can swap the map between the ring pick and this
            # lookup — a departed scheduler fails THIS task, not the job run
            scheduler = self.schedulers.get(scheduler_name)
            if scheduler is None:
                failures[task_id] = f"scheduler {scheduler_name} departed"
                continue
            # TriggerDownloadTask to the seed daemon (preheat.go:90-286 ->
            # scheduler job.go:152 -> seed ObtainSeeds) — NOT a proxy peer
            # registration: a peer registered on the seed's behalf has no
            # connection to receive responses, so nothing would download.
            ok = scheduler.trigger_seed_download(
                task_id=task_id,
                url=url,
                piece_length=req.piece_length,
                tag=req.tag,
                application=req.application,
                host_id=seed_host_id,
                headers=headers,
            )
            if not ok:
                failures[task_id] = "seed trigger rejected (queue full, no seed hosts, or scheduler unreachable)"
        # Enqueueing triggers is not a warm cluster: the job stays PENDING
        # until `get()` observes every task SUCCEEDED on its scheduler
        # (machinery group semantics — the reference's preheat e2e polls
        # the job state until the layers actually landed). No work at all
        # (empty urls) is an immediately-successful no-op, not a job that
        # pends forever.
        if failures:
            state = JobState.FAILURE
        elif not task_ids:
            state = JobState.SUCCESS
        else:
            state = JobState.PENDING
        result = JobResult(job_id, state, task_ids, {"failures": failures})
        self.jobs[job_id] = result
        return result

    def sync_peers(self) -> dict[str, dict]:
        """Per-scheduler entity counts plus each scheduler's announced-host
        list (scheduler/job/job.go:224 responds with its peers). The
        MANAGER layer merges `announced_hosts` into its peers table
        (manager/service.py create_job — it owns the database and the
        upsert idiom); this stays a pure data collection."""
        out = {}
        for name, s in self.schedulers.items():
            try:
                if isinstance(s, RemoteScheduler):
                    counts, hosts = s.info()  # one round trip, not two
                else:
                    counts, hosts = s.counts(), s.list_hosts()
            except ConnectionError as e:
                # an unreachable scheduler must not masquerade as a
                # healthy EMPTY one — the peer-table merge and operators
                # need to tell the two apart
                out[name] = {"unreachable": str(e), "announced_hosts": []}
                continue
            out[name] = {**counts, "announced_hosts": hosts}
        return out

    def get(self, job_id: str) -> JobResult | None:
        """Job state recomputed from LIVE task progress: a preheat is
        PENDING until every fanned-out task has actually completed on its
        owning scheduler (the reference's machinery group state the e2e
        preheat tests poll, internal/job group states + test/e2e/manager/
        preheat.go) — enqueue-time SUCCESS would claim a warm cluster
        before any seed finished downloading."""
        result = self.jobs.get(job_id)
        # Only ENQUEUE-TIME failures are terminal; a FAILED observed from
        # task polling must keep recomputing — a retried seed download can
        # recover the task (FSM allows FAILED -> SUCCEEDED), and latching
        # would make the job outcome depend on poll timing. SUCCESS *is*
        # terminal: once every task was observed SUCCEEDED the layers
        # landed, and a scheduler later forgetting the task (restart,
        # capacity eviction, TTL GC) must not regress a completed job
        # back to PENDING.
        if result is None or result.detail.get("failures") or not result.task_ids:
            return result
        if result.state == JobState.SUCCESS:
            return result
        from dragonfly2_tpu.state.fsm import TaskState

        # Per-task terminal SUCCEEDED outcomes latch across polls: task
        # TTL GC (or a scheduler restart) forgetting a completed task must
        # not regress it — without the latch a job whose tasks all
        # succeeded between polls would report PENDING forever once the
        # sweep reclaimed them (ADVICE r3). A task observed alive earlier
        # but now unknown WITHOUT a latched outcome is indeterminate and
        # expires the job.
        done, seen = self._latches.setdefault(result.job_id, ({}, {}))
        # One batched TaskStates call per owning scheduler (the wire
        # message takes a list): per-task round trips made a 50-URL poll
        # pay 50 dials — minutes against a briefly-down scheduler.
        by_owner: dict[str, list[str]] = {}
        to_poll = [t for t in result.task_ids if not done.get(t)]
        for task_id in to_poll:
            name = self.ring.pick(task_id)
            if name is not None:
                by_owner.setdefault(name, []).append(task_id)
        polled: dict[str, int | None] = {}
        unreachable = False
        for task_id in to_poll:
            if self.ring.pick(task_id) is None:
                # no owner at all (empty ring): the task is gone-for-good
                # as far as this manager can tell — same semantics as a
                # reachable scheduler answering "unknown task"
                polled[task_id] = None
        for name, tids in by_owner.items():
            svc = self.schedulers.get(name)
            if svc is None:
                # owner departed between the ring pick and the lookup:
                # permanently-unknown, NOT a transient transport failure —
                # holding position forever would leave the job PENDING
                # after a decommission (review r5)
                for tid in tids:
                    polled[tid] = None
                continue
            try:
                # Locked snapshot: this runs on manager REST threads while
                # the scheduler event loop mutates task state.
                for tid, raw in zip(tids, svc.task_states(tids)):
                    polled[tid] = raw
            except ConnectionError:
                # transport failure is NOT "scheduler forgot the task":
                # skip these tasks this round (last observations stand)
                # rather than expiring a healthy in-flight job
                unreachable = True
        states = []
        expired = False
        for task_id in result.task_ids:
            if done.get(task_id):
                states.append(TaskState.SUCCEEDED)
                continue
            raw = polled.get(task_id)
            if task_id not in polled:
                # unreachable scheduler (or no owner): hold position
                states.append(TaskState(seen[task_id])
                              if seen.get(task_id) is not None
                              else TaskState.PENDING)
            elif raw is None:
                if seen.get(task_id) == int(TaskState.FAILED):
                    # last observation before the task vanished was FAILED
                    # and no recovery was ever seen: the observation
                    # stands — a known-failed job must not drift to
                    # EXPIRED/PENDING just because GC reclaimed the task
                    states.append(TaskState.FAILED)
                elif seen.get(task_id) is not None:
                    expired = True
                    states.append(TaskState.PENDING)
                else:
                    states.append(TaskState.PENDING)  # seed not started yet
            else:
                state = TaskState(raw)
                seen[task_id] = int(state)
                if state == TaskState.SUCCEEDED:
                    done[task_id] = True
                states.append(state)
        # PER-TASK undelivered check: any task that NEVER appeared on a
        # reachable scheduler past the trigger-delivery TTL is
        # undeliverable (its trigger was dropped after SEED_TRIGGER_TTL_S
        # with only a log line) — a job-global flag would let one
        # delivered task mask a dropped sibling and pend the job forever.
        undelivered = [
            t for t in result.task_ids
            if not done.get(t) and seen.get(t) is None
        ]
        if (undelivered and not unreachable
                and time.monotonic() - result.created_at > SEED_START_TTL_S):
            result.state = JobState.EXPIRED
            result.detail["expired_reason"] = (
                f"{len(undelivered)} task(s) never picked up by any seed "
                "daemon within the delivery TTL"
            )
            result.detail["undelivered_task_ids"] = undelivered[:20]
            return result
        if any(s == TaskState.FAILED for s in states):
            result.state = JobState.FAILURE
            result.detail["task_states"] = [s.name for s in states]
        elif all(s == TaskState.SUCCEEDED for s in states):
            result.state = JobState.SUCCESS
            # SUCCESS is the one truly terminal outcome (the early return
            # above never recomputes it), so its latch bookkeeping is dead
            # weight from here on — without this pop the per-task maps
            # grow for every job over the manager's lifetime (ADVICE r4
            # low). FAILURE/EXPIRED keep their latches: both keep
            # recomputing because a retried seed can still recover.
            self._latches.pop(result.job_id, None)
        elif expired:
            result.state = JobState.EXPIRED
            result.detail["task_states"] = [s.name for s in states]
        else:
            result.state = JobState.PENDING
        return result
