"""Peer/Task state machines and host types as integer enums.

Capability parity with the reference's looplab/fsm-driven entities:
peer states/events (scheduler/resource/peer.go:53-109), task states
(scheduler/resource/task.go:58-84), host types (pkg/types/types.go:84-93).

TPU-first difference: states are small ints so they live in the
struct-of-arrays cluster state and are compared *inside* jitted kernels
(e.g. the bad-node state set in ops/evaluator.py); the transition table is
validated host-side at mutation time, exactly where the reference calls
``FSM.Event``.
"""

from __future__ import annotations

import enum


class HostType(enum.IntEnum):
    NORMAL = 0
    SUPER = 1       # seed peer
    STRONG = 2
    WEAK = 3

    @classmethod
    def from_name(cls, name: str) -> "HostType":
        return _HOST_TYPE_NAMES.get(name.lower(), cls.NORMAL)


_HOST_TYPE_NAMES = {
    "normal": HostType.NORMAL,
    "super": HostType.SUPER,
    "strong": HostType.STRONG,
    "weak": HostType.WEAK,
}


class PeerState(enum.IntEnum):
    PENDING = 0
    RECEIVED_EMPTY = 1
    RECEIVED_TINY = 2
    RECEIVED_SMALL = 3
    RECEIVED_NORMAL = 4
    RUNNING = 5
    BACK_TO_SOURCE = 6
    SUCCEEDED = 7
    FAILED = 8
    LEAVE = 9

    @classmethod
    def from_name(cls, name: str) -> "PeerState":
        return _PEER_STATE_NAMES.get(name, cls.PENDING)

    @property
    def display(self) -> str:
        return _PEER_STATE_DISPLAY[self]


_PEER_STATE_DISPLAY = {
    PeerState.PENDING: "Pending",
    PeerState.RECEIVED_EMPTY: "ReceivedEmpty",
    PeerState.RECEIVED_TINY: "ReceivedTiny",
    PeerState.RECEIVED_SMALL: "ReceivedSmall",
    PeerState.RECEIVED_NORMAL: "ReceivedNormal",
    PeerState.RUNNING: "Running",
    PeerState.BACK_TO_SOURCE: "BackToSource",
    PeerState.SUCCEEDED: "Succeeded",
    PeerState.FAILED: "Failed",
    PeerState.LEAVE: "Leave",
}
_PEER_STATE_NAMES = {v: k for k, v in _PEER_STATE_DISPLAY.items()}


class PeerEvent(enum.IntEnum):
    REGISTER_EMPTY = 0
    REGISTER_TINY = 1
    REGISTER_SMALL = 2
    REGISTER_NORMAL = 3
    DOWNLOAD = 4
    DOWNLOAD_BACK_TO_SOURCE = 5
    DOWNLOAD_SUCCEEDED = 6
    DOWNLOAD_FAILED = 7
    LEAVE = 8


# event -> (allowed source states, destination state); peer.go:137-221 wiring.
PEER_TRANSITIONS: dict[PeerEvent, tuple[frozenset[PeerState], PeerState]] = {
    PeerEvent.REGISTER_EMPTY: (frozenset({PeerState.PENDING}), PeerState.RECEIVED_EMPTY),
    PeerEvent.REGISTER_TINY: (frozenset({PeerState.PENDING}), PeerState.RECEIVED_TINY),
    PeerEvent.REGISTER_SMALL: (frozenset({PeerState.PENDING}), PeerState.RECEIVED_SMALL),
    PeerEvent.REGISTER_NORMAL: (frozenset({PeerState.PENDING}), PeerState.RECEIVED_NORMAL),
    PeerEvent.DOWNLOAD: (
        frozenset({
            PeerState.RECEIVED_EMPTY,
            PeerState.RECEIVED_TINY,
            PeerState.RECEIVED_SMALL,
            PeerState.RECEIVED_NORMAL,
        }),
        PeerState.RUNNING,
    ),
    PeerEvent.DOWNLOAD_BACK_TO_SOURCE: (
        frozenset({
            PeerState.RECEIVED_EMPTY,
            PeerState.RECEIVED_TINY,
            PeerState.RECEIVED_SMALL,
            PeerState.RECEIVED_NORMAL,
            PeerState.RUNNING,
        }),
        PeerState.BACK_TO_SOURCE,
    ),
    PeerEvent.DOWNLOAD_SUCCEEDED: (
        frozenset({PeerState.RUNNING, PeerState.BACK_TO_SOURCE}),
        PeerState.SUCCEEDED,
    ),
    PeerEvent.DOWNLOAD_FAILED: (
        frozenset({
            PeerState.RUNNING,
            PeerState.BACK_TO_SOURCE,
            PeerState.SUCCEEDED,
        }),
        PeerState.FAILED,
    ),
    PeerEvent.LEAVE: (
        frozenset(s for s in PeerState if s != PeerState.LEAVE),
        PeerState.LEAVE,
    ),
}


class TaskState(enum.IntEnum):
    PENDING = 0
    RUNNING = 1
    SUCCEEDED = 2
    FAILED = 3
    LEAVE = 4

    @classmethod
    def from_name(cls, name: str) -> "TaskState":
        return _TASK_STATE_NAMES.get(name, cls.PENDING)

    @property
    def display(self) -> str:
        return _TASK_STATE_DISPLAY[self]


_TASK_STATE_DISPLAY = {
    TaskState.PENDING: "Pending",
    TaskState.RUNNING: "Running",
    TaskState.SUCCEEDED: "Succeeded",
    TaskState.FAILED: "Failed",
    TaskState.LEAVE: "Leave",
}
_TASK_STATE_NAMES = {v: k for k, v in _TASK_STATE_DISPLAY.items()}


class TaskEvent(enum.IntEnum):
    DOWNLOAD = 0
    DOWNLOAD_SUCCEEDED = 1
    DOWNLOAD_FAILED = 2
    LEAVE = 3


TASK_TRANSITIONS: dict[TaskEvent, tuple[frozenset[TaskState], TaskState]] = {
    TaskEvent.DOWNLOAD: (
        frozenset({TaskState.PENDING, TaskState.SUCCEEDED, TaskState.FAILED, TaskState.LEAVE}),
        TaskState.RUNNING,
    ),
    TaskEvent.DOWNLOAD_SUCCEEDED: (
        frozenset({TaskState.RUNNING, TaskState.FAILED}),
        TaskState.SUCCEEDED,
    ),
    TaskEvent.DOWNLOAD_FAILED: (frozenset({TaskState.RUNNING}), TaskState.FAILED),
    TaskEvent.LEAVE: (frozenset(s for s in TaskState if s != TaskState.LEAVE), TaskState.LEAVE),
}


class InvalidTransition(Exception):
    pass


def peer_transition(current: PeerState, event: PeerEvent) -> PeerState:
    sources, dest = PEER_TRANSITIONS[event]
    if current not in sources:
        raise InvalidTransition(f"peer event {event.name} invalid from state {current.name}")
    return dest


def task_transition(current: TaskState, event: TaskEvent) -> TaskState:
    sources, dest = TASK_TRANSITIONS[event]
    if current not in sources:
        raise InvalidTransition(f"task event {event.name} invalid from state {current.name}")
    return dest


# States for which IsBadNode short-circuits to True (evaluator.go:93-99):
# Failed, Leave, Pending, and all Received* states.
BAD_NODE_STATES = frozenset({
    PeerState.FAILED,
    PeerState.LEAVE,
    PeerState.PENDING,
    PeerState.RECEIVED_EMPTY,
    PeerState.RECEIVED_TINY,
    PeerState.RECEIVED_SMALL,
    PeerState.RECEIVED_NORMAL,
})
