"""Span tracing at service boundaries.

Capability parity with the reference's OpenTelemetry usage: every binary
initializes a tracer with an exporter (cmd/dependency/dependency.go:263-280
jaeger flag) and services create spans at boundaries (scheduler service,
client conductor/piece_downloader, manager jobs). This implementation is
OTel-shaped (trace_id/span_id/parent, attributes, events, status) with
pluggable exporters: in-memory (tests), JSONL file, or a user callable —
zero required external infrastructure.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import json
import pathlib
import secrets
import threading
import time
from typing import Any, Callable

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "dragonfly2_tpu_span", default=None
)


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start_ns: int
    end_ns: int | None = None
    attributes: dict[str, Any] = dataclasses.field(default_factory=dict)
    events: list[dict] = dataclasses.field(default_factory=list)
    status: str = "OK"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def add_event(self, name: str, **attrs) -> None:
        self.events.append({"name": name, "ts_ns": time.time_ns(), **attrs})

    def record_exception(self, exc: BaseException) -> None:
        self.status = "ERROR"
        self.add_event("exception", type=type(exc).__name__, message=str(exc))

    def duration_ms(self) -> float | None:
        if self.end_ns is None:
            return None
        return (self.end_ns - self.start_ns) / 1e6

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Tracer:
    def __init__(self, service: str = "dragonfly2-tpu"):
        self.service = service
        self._exporters: list[Callable[[Span], None]] = []
        self._lock = threading.Lock()
        # Spans started but not yet ended, keyed by span_id — the flight
        # recorder dumps these so an operator can see what a slow tick is
        # CURRENTLY inside of, not only what already finished.
        self._active: dict[str, Span] = {}

    def add_exporter(self, fn: Callable[[Span], None]) -> None:
        with self._lock:
            self._exporters.append(fn)

    def remove_exporter(self, fn: Callable[[Span], None]) -> None:
        """Detach by identity; the process-global tracer outlives tests
        and short-lived consumers, which must not leak exporters into it."""
        with self._lock:
            self._exporters = [f for f in self._exporters if f is not fn]

    def export_to_memory(self) -> list[Span]:
        """Attach an in-memory exporter; returns the live list of spans."""
        spans: list[Span] = []
        self.add_exporter(spans.append)
        return spans

    def export_to_file(self, path: str | pathlib.Path) -> "FileSpanExporter":
        """Attach a JSONL file exporter holding ONE open handle with
        locked writes (the old closure reopened the file once per span —
        measurable fd churn on a busy tracer). Returns the exporter so
        the caller can ``close()`` it (and ``remove_exporter`` it) when
        done; the JSONL format is byte-identical to the per-span-open
        implementation."""
        exporter = FileSpanExporter(path)
        self.add_exporter(exporter)
        return exporter

    @contextlib.contextmanager
    def span(self, name: str, remote_parent: dict | None = None, **attributes):
        """Open a span under the ambient parent, or — when `remote_parent`
        is a wire-propagated context ({"trace_id", "span_id"}, rpc/wire.py
        frame envelope) — continue the REMOTE trace: the explicit context
        wins over the contextvar so a server-side handler parents on the
        caller's span, not on whatever local span happens to be open."""
        parent = _current_span.get()
        if remote_parent and remote_parent.get("trace_id"):
            trace_id = str(remote_parent["trace_id"])
            parent_id = str(remote_parent.get("span_id") or "") or None
        else:
            trace_id = parent.trace_id if parent else secrets.token_hex(16)
            parent_id = parent.span_id if parent else None
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=secrets.token_hex(8),
            parent_id=parent_id,
            start_ns=time.time_ns(),
            attributes={"service": self.service, **attributes},
        )
        token = _current_span.set(span)
        with self._lock:
            self._active[span.span_id] = span
        try:
            yield span
        except BaseException as e:
            span.record_exception(e)
            raise
        finally:
            span.end_ns = time.time_ns()
            _current_span.reset(token)
            with self._lock:
                self._active.pop(span.span_id, None)
                exporters = list(self._exporters)
            for fn in exporters:
                try:
                    fn(span)
                except Exception:  # noqa: BLE001 - exporters must not break the traced path
                    pass

    def active_spans(self) -> list[Span]:
        """Snapshot of spans currently open (started, not ended)."""
        with self._lock:
            return list(self._active.values())


class FileSpanExporter:
    """JSONL span sink over one held file handle.

    Writes are serialized by a lock and flushed per span (the per-span
    reopen it replaces flushed implicitly on close, and external readers
    tail the file). After ``close()`` further spans are dropped silently
    — an exporter must never break the traced path."""

    def __init__(self, path: str | pathlib.Path):
        path = pathlib.Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        self._f = open(path, "a")
        self._lock = threading.Lock()

    def __call__(self, span: Span) -> None:
        line = json.dumps(span.to_dict()) + "\n"
        with self._lock:
            if self._f.closed:
                return
            self._f.write(line)
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if not self._f.closed:
                self._f.close()


def current_span() -> Span | None:
    return _current_span.get()


def current_context() -> dict | None:
    """Wire-propagatable context of the ambient span (None outside any
    span). rpc/wire.encode stamps this into the frame envelope so a trace
    started on one side of an RPC continues on the other."""
    span = _current_span.get()
    if span is None:
        return None
    return {"trace_id": span.trace_id, "span_id": span.span_id}


# ------------------------------------------------------------------ OTLP


def _otlp_value(value: Any) -> dict:
    """Python scalar -> OTLP/JSON AnyValue (int64 rides as a string per
    the protobuf JSON mapping)."""
    if isinstance(value, bool):
        return {"boolValue": value}
    if isinstance(value, int):
        return {"intValue": str(value)}
    if isinstance(value, float):
        return {"doubleValue": value}
    return {"stringValue": str(value)}


def _otlp_attributes(attrs: dict) -> list[dict]:
    return [{"key": k, "value": _otlp_value(v)} for k, v in attrs.items()]


def span_to_otlp(span: Span) -> dict:
    """One Span -> an OTLP/JSON span object (opentelemetry-proto
    trace/v1, the wire shape `POST /v1/traces` collectors ingest)."""
    out = {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(span.start_ns),
        "endTimeUnixNano": str(span.end_ns or span.start_ns),
        "attributes": _otlp_attributes(span.attributes),
        "events": [
            {
                "timeUnixNano": str(e.get("ts_ns", span.start_ns)),
                "name": e.get("name", ""),
                "attributes": _otlp_attributes(
                    {k: v for k, v in e.items() if k not in ("name", "ts_ns")}
                ),
            }
            for e in span.events
        ],
        "status": {"code": 2 if span.status == "ERROR" else 1},
    }
    if span.parent_id:
        out["parentSpanId"] = span.parent_id
    return out


def spans_to_otlp_request(spans: list[Span], service: str) -> dict:
    """ExportTraceServiceRequest JSON body for a span batch."""
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": _otlp_attributes({"service.name": service})
                },
                "scopeSpans": [
                    {
                        "scope": {"name": "dragonfly2-tpu"},
                        "spans": [span_to_otlp(s) for s in spans],
                    }
                ],
            }
        ]
    }


class OTLPExporter:
    """Batching OTLP/HTTP-JSON trace exporter (the reference initializes a
    Jaeger exporter per binary, cmd/dependency/dependency.go:263-280; OTLP
    is what that stack speaks today — any collector/Jaeger >=1.35 ingests
    `POST <endpoint>/v1/traces`). Buffers spans; full batches are handed to
    a daemon worker thread so span-end NEVER blocks the caller (the tracer
    runs inside asyncio handlers — a slow collector must not stall the
    event loop, the reference's BatchSpanProcessor makes the same call).
    `flush()` posts the partial buffer AND drains batches already queued
    to the worker (shutdown/tests — a queued-but-unposted batch must not
    be lost just because the daemon worker hadn't gotten to it);
    `close()` flushes, then stops the worker via a sentinel with a
    bounded join. Network failures drop the batch with a log line, never
    break the traced path."""

    def __init__(self, endpoint: str, service: str = "dragonfly2-tpu",
                 batch_size: int = 64, timeout: float = 10.0):
        import queue

        self.endpoint = endpoint.rstrip("/")
        self.service = service
        self.batch_size = batch_size
        self.timeout = timeout
        self._buf: list[Span] = []
        self._lock = threading.Lock()
        self._queue: "queue.Queue[list[Span] | None]" = queue.Queue(maxsize=16)
        self._worker: threading.Thread | None = None
        self._closed = False

    def _ensure_worker(self) -> None:
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._drain, name="otlp-exporter", daemon=True
            )
            self._worker.start()

    def _drain(self) -> None:
        while True:
            batch = self._queue.get()
            if batch is None:  # close() sentinel
                return
            self._post(batch)

    def export(self, span: Span) -> None:
        with self._lock:
            if self._closed:
                return  # spans after close drop silently, like a full queue
            self._buf.append(span)
            if len(self._buf) < self.batch_size:
                return
            batch, self._buf = self._buf, []
        self._ensure_worker()
        try:
            self._queue.put_nowait(batch)
        except Exception:  # noqa: BLE001 - full queue: drop, never block
            import logging

            logging.getLogger(__name__).warning(
                "OTLP export queue full; dropping a %d-span batch", len(batch)
            )

    def flush(self) -> None:
        """Synchronously post everything buffered ANYWHERE in the
        exporter: batches already handed to the daemon worker's queue
        (drained here, not abandoned to a thread that may never run
        again) and then the partial in-progress buffer."""
        import queue

        while True:
            try:
                batch = self._queue.get_nowait()
            except queue.Empty:
                break
            if batch is None:
                # close()'s shutdown sentinel: hand it back to the worker
                # and stop — swallowing it here would leave the worker
                # blocked in get() forever (and close() burning its full
                # join timeout). Nothing can be queued behind it: close()
                # enqueues it only after _closed blocks further exports.
                try:
                    self._queue.put_nowait(None)
                except Exception:  # noqa: BLE001 - full queue: worker will still see EOF via close()'s retry
                    pass
                break
            self._post(batch)
        with self._lock:
            batch, self._buf = self._buf, []
        if batch:
            self._post(batch)

    def close(self, timeout: float = 5.0) -> None:
        """Bounded shutdown: flush every queued/partial span, then stop
        the worker via sentinel and join it for at most ``timeout``
        seconds. Idempotent; later export() calls drop silently."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self.flush()
        worker = self._worker
        if worker is not None and worker.is_alive():
            try:
                self._queue.put_nowait(None)
            except Exception:  # noqa: BLE001 - full queue: join is still bounded
                pass
            worker.join(timeout)
        self._worker = None

    def _post(self, batch: list[Span]) -> None:
        import logging
        import urllib.error
        import urllib.request

        body = json.dumps(spans_to_otlp_request(batch, self.service)).encode()
        req = urllib.request.Request(
            f"{self.endpoint}/v1/traces",
            data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
        except urllib.error.URLError as e:
            logging.getLogger(__name__).warning(
                "OTLP export of %d spans to %s failed: %s",
                len(batch), self.endpoint, e,
            )


_DEFAULT = Tracer()


def default_tracer() -> Tracer:
    return _DEFAULT
