"""dflint green fixture: the bucketed idioms the shape pass must prove.

Batch dims arrive through the bucket machinery (``_EVAL_BUCKETS``
iteration, ``_bucket_rows``), arrays through the padding helpers
(``_pad_rows``), statics from config attributes — the exact shapes of
warmup() and _dispatch_chunk in cluster/scheduler.py. All silent.
"""

import numpy as np

from dragonfly2_tpu.cluster.scheduler import (
    _EVAL_BUCKETS,
    _bucket_rows,
    _pad_rows,
)
from dragonfly2_tpu.ops import evaluator as ev


def warm_all_buckets(fd, k, c, l, n, config):
    limit = config.scheduler.candidate_parent_limit  # config: fixed
    for bsz in _EVAL_BUCKETS:  # bucket-set iteration
        buf = ev.pack_eval_batch(fd)
        out = ev.schedule_from_packed(buf, bsz, k, c, l, n, limit=limit)
        np.asarray(out)


def dispatch_chunk(fd, s, e, k, c, l, n):
    bsz = _bucket_rows(e - s)  # bucket producer
    buf = ev.pack_eval_batch(
        {name: _pad_rows(v[s:e], bsz) for name, v in fd.items()}
    )
    return ev.schedule_from_packed(buf, bsz, k, c, l, n)
