"""Scheduler v1 compat surface — the schedulerv1 dialect as an adapter.

Capability parity with the reference's V1 service
(scheduler/service/service_v1.go): RegisterPeerTask (:95),
ReportPieceResult (:187, the bidi piece stream answered with PeerPacket
parent reassignments), ReportPeerResult (:294), AnnounceTask (:349),
StatTask (:434), LeaveTask (:457). The reference serves BOTH protocol
generations against one resource layer; this repo's native protocol is
the v2-shaped message set (cluster/messages.py), and this module closes
the gap the same way: v1-dialect dataclasses over the same wire codec,
each translated onto the existing SchedulerService handlers, scheduling
responses translated back into v1 ``PeerPacket`` frames
(rpc/server.py routes per-peer responses through ``to_peer_packet`` for
connections that registered via v1).

Size-scope mapping (service_v1.go:1005-1110): EMPTY short-circuits at
register like the reference's registerEmptyTask; TINY/SMALL register and
take the normal scheduling path — the reference itself falls back to
registerNormalTask whenever the direct piece / single parent is not
available (:1021-1110), and this scheduler never holds piece bytes.

Codes mirror the public api common.proto v1 enum semantics the v1
clients switch on (Success / SchedError / SchedNeedBackSource /
SchedPeerGone).
"""

from __future__ import annotations

import dataclasses
import typing

import numpy as np

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.state.fsm import PeerState, TaskState
from dragonfly2_tpu.utils import idgen

# pkg/rpc/common/common.go:20-24
BEGIN_OF_PIECE = -1
END_OF_PIECE = 1 << 30

# api common.proto v1 Code values the v1 dialect switches on
CODE_SUCCESS = 200
CODE_SCHED_ERROR = 5000
CODE_SCHED_NEED_BACK_SOURCE = 5001
CODE_SCHED_PEER_GONE = 5002
# client-side piece verification failure (the common.proto client-error
# band): a v1 peer reporting this code means the piece's bytes failed its
# digest check — translated onto the v2 reason="corruption" quarantine
# path, mirroring the reference's md5-mismatch piece-result handling.
CODE_CLIENT_PIECE_MD5_NOT_MATCHED = 4004


@dataclasses.dataclass
class V1PeerHost:
    """schedulerv1.PeerHost."""

    id: str
    ip: str = ""
    rpc_port: int = 8002
    down_port: int = 8001
    host_name: str = ""
    security_domain: str = ""
    location: str = ""
    idc: str = ""


@dataclasses.dataclass
class V1UrlMeta:
    """commonv1.UrlMeta."""

    digest: str = ""
    tag: str = ""
    range: str = ""
    filter: str = ""
    application: str = ""
    priority: int = 0


@dataclasses.dataclass
class V1PeerTaskRequest:
    """schedulerv1.PeerTaskRequest (RegisterPeerTask input)."""

    url: str
    peer_id: str
    peer_host: V1PeerHost
    url_meta: V1UrlMeta = dataclasses.field(default_factory=V1UrlMeta)
    task_id: str = ""
    is_migrating: bool = False
    prefetch: bool = False


@dataclasses.dataclass
class V1RegisterResult:
    task_id: str
    size_scope: int = int(msg.SizeScope.NORMAL)
    code: int = CODE_SUCCESS


@dataclasses.dataclass
class V1PieceInfo:
    """commonv1.PieceInfo (the subset the scheduler consumes)."""

    piece_num: int = 0
    range_start: int = 0
    range_size: int = 0
    piece_md5: str = ""
    piece_offset: int = 0
    download_cost: int = 0  # milliseconds, like the reference's cost field


@dataclasses.dataclass
class V1PieceResult:
    """schedulerv1.PieceResult — one frame of the ReportPieceResult stream."""

    task_id: str
    src_pid: str
    dst_pid: str = ""
    success: bool = False
    code: int = CODE_SUCCESS
    piece_info: V1PieceInfo = dataclasses.field(default_factory=V1PieceInfo)
    finished_count: int = 0

    @property
    def peer_id(self) -> str:  # server routing key (rpc/server.py)
        return self.src_pid


@dataclasses.dataclass
class V1DestPeer:
    ip: str
    rpc_port: int
    peer_id: str


@dataclasses.dataclass
class V1PeerPacket:
    """schedulerv1.PeerPacket — the scheduling answer streamed to a v1 peer."""

    task_id: str
    src_pid: str
    parallel_count: int = 1
    # typing.Optional (not PEP-604 `| None`): the wire codec resolves
    # Optional through typing.get_origin == typing.Union (rpc/wire.py)
    main_peer: typing.Optional[V1DestPeer] = None
    candidate_peers: list[V1DestPeer] = dataclasses.field(default_factory=list)
    code: int = CODE_SUCCESS


@dataclasses.dataclass
class V1PeerResult:
    """schedulerv1.PeerResult (ReportPeerResult input)."""

    task_id: str
    peer_id: str
    src_ip: str = ""
    traffic: int = 0
    cost: int = 0
    success: bool = False
    code: int = CODE_SUCCESS
    total_piece_count: int = 0
    content_length: int = -1


@dataclasses.dataclass
class V1PeerTarget:
    """schedulerv1.PeerTarget (LeaveTask input)."""

    task_id: str
    peer_id: str


@dataclasses.dataclass
class V1AnnounceTaskRequest:
    """schedulerv1.AnnounceTaskRequest: a peer already holds the whole
    task (dfcache import path) — the scheduler records host+task+peer as
    SUCCEEDED so the peer is immediately schedulable as a parent."""

    task_id: str
    url: str
    peer_host: V1PeerHost
    peer_id: str
    url_meta: V1UrlMeta = dataclasses.field(default_factory=V1UrlMeta)
    total_piece_count: int = 0
    content_length: int = -1


@dataclasses.dataclass
class V1Task:
    """schedulerv1.Task (StatTask response)."""

    id: str
    type: int = 0
    content_length: int = -1
    total_piece_count: int = 0
    state: str = ""
    peer_count: int = 0
    has_available_peer: bool = False


class SchedulerServiceV1:
    """Translates the v1 dialect onto a SchedulerService instance. All
    methods expect the caller to hold service.mu (the RPC server's
    dispatch already does)."""

    def __init__(self, service):
        self.svc = service

    @staticmethod
    def _host_info(peer_host: V1PeerHost) -> msg.HostInfo:
        return msg.HostInfo(
            host_id=peer_host.id,
            hostname=peer_host.host_name,
            ip=peer_host.ip,
            port=peer_host.rpc_port,
            download_port=peer_host.down_port,
            idc=peer_host.idc,
            location=peer_host.location,
        )

    # ----------------------------------------------------------- register

    def register_peer_task(self, req: V1PeerTaskRequest) -> V1RegisterResult:
        """service_v1.go:95 — store task/host/peer, trigger the seed on a
        cold task, answer the size scope. Content length is unknown at v1
        register time (the origin probe lives client-side), so only an
        explicitly-empty range registers EMPTY; everything else schedules
        as NORMAL, the reference's own fallback for missing direct
        pieces (:1021-1110)."""
        task_id = req.task_id or idgen.task_id_v1(
            req.url,
            tag=req.url_meta.tag,
            application=req.url_meta.application,
            filtered_query_params=req.url_meta.filter,
        )
        host = self._host_info(req.peer_host)
        v2 = msg.RegisterPeerRequest(
            peer_id=req.peer_id,
            task_id=task_id,
            host=host,
            url=req.url,
            priority=req.url_meta.priority,
            tag=req.url_meta.tag,
            application=req.url_meta.application,
        )
        response = self.svc.register_peer(v2)
        if isinstance(response, msg.EmptyTaskResponse):
            return V1RegisterResult(task_id=task_id, size_scope=int(msg.SizeScope.EMPTY))
        return V1RegisterResult(task_id=task_id, size_scope=int(msg.SizeScope.NORMAL))

    # -------------------------------------------------------- piece stream

    def report_piece_result(self, res: V1PieceResult):
        """service_v1.go:187 — one piece frame. Returns a v2-shaped
        response (or None); the caller converts tick/stream responses for
        v1 connections with `to_peer_packet`. Success frames land in the
        scheduler's buffered piece-report ingestion (absorbed into the
        SoA columns once per tick, report_ingest phase) — the v1 stream
        shares the columnar control plane with v2."""
        num = res.piece_info.piece_num
        if num == BEGIN_OF_PIECE:
            # handleBeginOfPiece (:1122): Received -> Running happens on
            # the v2 register path already; nothing to replay.
            return None
        if num == END_OF_PIECE:
            return None  # handleEndOfPiece is a no-op (:1156)
        if res.success:
            return self.svc.handle(msg.DownloadPieceFinishedRequest(
                peer_id=res.src_pid,
                piece_number=num,
                parent_peer_id=res.dst_pid,
                length=res.piece_info.range_size,
                cost_ns=int(res.piece_info.download_cost) * 1_000_000,
            ))
        # handlePieceFailure (:1210): blocklist the failed parent and
        # reschedule — the v2 piece-failed handler does exactly that. An
        # md5-mismatch code rides through as reason="corruption" so v1
        # peers feed the same quarantine path as v2 ones.
        return self.svc.handle(msg.DownloadPieceFailedRequest(
            peer_id=res.src_pid,
            parent_peer_id=res.dst_pid,
            reason=(
                "corruption"
                if res.code == CODE_CLIENT_PIECE_MD5_NOT_MATCHED else ""
            ),
        ))

    # ------------------------------------------------------- final result

    def report_peer_result(self, res: V1PeerResult):
        """service_v1.go:294 — route by success x back-to-source, exactly
        the reference's four-way dispatch onto the v2 handlers."""
        idx = self.svc.state.peer_index(res.peer_id)
        if idx is None:
            return V1PeerPacket(
                task_id=res.task_id, src_pid=res.peer_id, code=CODE_SCHED_PEER_GONE
            )
        back_to_source = (
            self.svc.state.peer_state[idx] == int(PeerState.BACK_TO_SOURCE)
        )
        if res.success:
            if back_to_source:
                self.svc.handle(msg.DownloadPeerBackToSourceFinishedRequest(
                    peer_id=res.peer_id, piece_count=res.total_piece_count,
                ))
            else:
                self.svc.handle(msg.DownloadPeerFinishedRequest(peer_id=res.peer_id))
        elif back_to_source:
            self.svc.handle(msg.DownloadPeerBackToSourceFailedRequest(peer_id=res.peer_id))
        else:
            self.svc.handle(msg.DownloadPeerFailedRequest(peer_id=res.peer_id))
        return None

    # ------------------------------------------------------------- others

    def announce_task(self, req: V1AnnounceTaskRequest) -> None:
        """service_v1.go:349 — register host/task/peer and drive both to
        SUCCEEDED so the announced replica serves immediately."""
        host = self._host_info(req.peer_host)
        self.svc.register_peer(msg.RegisterPeerRequest(
            peer_id=req.peer_id,
            task_id=req.task_id,
            host=host,
            url=req.url,
            content_length=max(req.content_length, -1),
            total_piece_count=req.total_piece_count,
            priority=1,  # no seed trigger for an already-complete replica
            tag=req.url_meta.tag,
            application=req.url_meta.application,
        ))
        idx = self.svc.state.peer_index(req.peer_id)
        if idx is not None:
            # one columnar batch instead of a per-piece record_piece loop
            # (an announced replica can carry thousands of pieces)
            n = max(req.total_piece_count, 1)
            self.svc.state.record_pieces_batch(
                np.full(n, int(idx), np.int64), np.arange(n), np.zeros(n)
            )
        self.svc.handle(msg.DownloadPeerFinishedRequest(peer_id=req.peer_id))

    def stat_task(self, req: msg.StatTaskRequest) -> V1Task:
        """service_v1.go:434."""
        st = self.svc.state
        idx = st.task_index(req.task_id)
        if idx is None:
            return V1Task(id=req.task_id, state="", peer_count=0)
        peers = self.svc._task_peers.get(req.task_id, [])
        has_available = False
        for pid in peers:
            pidx = st.peer_index(pid)
            if pidx is not None and st.peer_state[pidx] == int(PeerState.SUCCEEDED):
                has_available = True
                break
        return V1Task(
            id=req.task_id,
            content_length=int(st.task_content_length[idx]),
            total_piece_count=int(st.task_total_pieces[idx]),
            state=TaskState(int(st.task_state[idx])).name,
            peer_count=len(peers),
            has_available_peer=has_available,
        )

    def leave_task(self, req: V1PeerTarget) -> None:
        """service_v1.go:457 — the peer leaves the task's swarm."""
        self.svc.leave_peer(req.peer_id)

    # ---------------------------------------------------------- responses

    def to_peer_packet(self, response) -> V1PeerPacket | None:
        """v2 scheduling response -> v1 PeerPacket for v1 connections."""
        if isinstance(response, msg.NormalTaskResponse):
            peers = [
                V1DestPeer(ip=p.ip, rpc_port=p.port, peer_id=p.peer_id)
                for p in response.candidate_parents
            ]
            meta = self.svc._peer_meta.get(response.peer_id)
            return V1PeerPacket(
                task_id=meta.task_id if meta else "",
                src_pid=response.peer_id,
                parallel_count=max(len(peers), 1),
                main_peer=peers[0] if peers else None,
                candidate_peers=peers[1:],
                code=CODE_SUCCESS,
            )
        if isinstance(response, msg.NeedBackToSourceResponse):
            meta = self.svc._peer_meta.get(response.peer_id)
            return V1PeerPacket(
                task_id=meta.task_id if meta else "",
                src_pid=response.peer_id,
                code=CODE_SCHED_NEED_BACK_SOURCE,
            )
        if isinstance(response, msg.ScheduleFailure):
            return V1PeerPacket(
                task_id="", src_pid=response.peer_id, code=CODE_SCHED_ERROR
            )
        if isinstance(response, msg.EmptyTaskResponse):
            return V1PeerPacket(
                task_id="", src_pid=response.peer_id, code=CODE_SUCCESS
            )
        return None


V1_REQUEST_TYPES = (
    V1PeerTaskRequest,
    V1PieceResult,
    V1PeerResult,
    V1PeerTarget,
    V1AnnounceTaskRequest,
)


# Register the dialect with the wire codec at import time (like
# rpc/inference.py does for its message set): any client or server that
# imports this module can speak it without also importing rpc/server.
# register_module picks up every dataclass defined here, so a future V1
# message cannot be forgotten from a hand-maintained list.
import sys as _sys  # noqa: E402

from dragonfly2_tpu.rpc import wire as _wire  # noqa: E402

_wire.register_module(_sys.modules[__name__])
