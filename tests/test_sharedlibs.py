"""Shared-libraries layer: cache, gc, retry, containers, errors, dynconfig,
plugins, dfpath, dflog — the pkg/ + internal/ equivalents (SURVEY.md §2.5)."""

import logging
import threading
import time

import pytest

from dragonfly2_tpu.utils import dferrors, dfpath, plugins, retry
from dragonfly2_tpu.utils.cache import Cache, CacheKeyExists
from dragonfly2_tpu.utils.container import Bitset, RingBuffer, SafeSet
from dragonfly2_tpu.utils.dynconfig import Dynconfig
from dragonfly2_tpu.utils.gc import GC, Task

# ------------------------------------------------------------------- cache


def test_cache_set_get_expire():
    c = Cache(default_expiration=0.05)
    c.set("a", 1)
    c.set("b", 2, ttl=10.0)
    c.set_default("forever", 3)  # default 0.05s
    c.set("never", 4, ttl=0)  # no expiration
    assert c.get("a") == 1
    time.sleep(0.08)
    assert c.get("a") is None
    assert c.get("b") == 2
    assert c.get("never") == 4


def test_cache_add_and_scan_and_keys():
    c = Cache()
    c.add("networktopology:h1:h2", 1)
    with pytest.raises(CacheKeyExists):
        c.add("networktopology:h1:h2", 2)
    c.set("networktopology:h1:h3", 2)
    c.set("probes:h1:h2", 3)
    assert sorted(c.scan("networktopology:")) == [
        "networktopology:h1:h2",
        "networktopology:h1:h3",
    ]
    assert c.scan("networktopology:", limit=1) == ["networktopology:h1:h2"] or len(
        c.scan("networktopology:", limit=1)
    ) == 1
    assert c.scan("probes:", limit=0) == []
    assert c.item_count() == 3


def test_cache_evicted_callback_and_janitor():
    c = Cache(default_expiration=0.03, cleanup_interval=0.02)
    evicted = []
    c.on_evicted(lambda k, v: evicted.append((k, v)))
    c.set("x", 42)
    time.sleep(0.12)
    assert ("x", 42) in evicted
    c.close()


def test_cache_save_load(tmp_path):
    c = Cache()
    c.set("k", {"nested": [1, 2]}, ttl=100)
    c.set("gone", 1, ttl=0.01)
    time.sleep(0.03)
    p = tmp_path / "cache.bin"
    c.save_file(str(p))
    c2 = Cache()
    c2.load_file(str(p))
    assert c2.get("k") == {"nested": [1, 2]}
    assert c2.get("gone") is None


# --------------------------------------------------------------------- gc


def test_gc_run_and_validation():
    runs = []
    g = GC()
    g.add(Task(id="t", interval=10.0, timeout=5.0, runner=lambda: runs.append(1)))
    with pytest.raises(ValueError):
        g.add(Task(id="t", interval=10.0, timeout=5.0, runner=lambda: None))
    with pytest.raises(ValueError):
        g.add(Task(id="bad", interval=1.0, timeout=2.0, runner=lambda: None))
    g.run("t")
    g.run_all()
    assert len(runs) == 2
    with pytest.raises(KeyError):
        g.run("missing")


def test_gc_periodic_and_restart():
    done = threading.Event()
    g = GC()
    g.add(Task(id="tick", interval=0.02, timeout=0.02, runner=done.set))
    g.start()
    assert done.wait(1.0)
    g.stop()
    # restart after stop: loops must run again, and tasks added after a
    # stop must actually tick
    done.clear()
    late = threading.Event()
    g.add(Task(id="late", interval=0.02, timeout=0.02, runner=late.set))
    g.start()
    assert done.wait(1.0) and late.wait(1.0)
    g.stop()


# ------------------------------------------------------------------- retry


def test_retry_succeeds_after_failures():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert retry.run(flaky, init_backoff=0.001, max_attempts=5) == "ok"
    assert calls["n"] == 3


def test_retry_exhausts_and_cancel():
    with pytest.raises(OSError):
        retry.run(lambda: (_ for _ in ()).throw(OSError("always")), init_backoff=0.001, max_attempts=2)

    def cancelled():
        raise retry.Cancel(ValueError("fatal"))

    with pytest.raises(ValueError, match="fatal"):
        retry.run(cancelled, init_backoff=0.001, max_attempts=5)


# -------------------------------------------------------------- containers


def test_safe_set():
    s = SafeSet([1, 2])
    assert s.add(3)
    assert not s.add(3)
    assert s.contains(1, 2, 3)
    s.delete(2)
    assert not s.contains(2)
    assert len(s) == 2


def test_bitset_finished_pieces():
    b = Bitset()
    for piece in (0, 63, 64, 1000):
        b.set(piece)
    assert b.test(63) and b.test(1000)
    assert not b.test(62) and not b.test(5000)
    assert b.count() == 4
    b.clear(63)
    assert not b.test(63)
    # round-trip through raw words (the device-array lift)
    b2 = Bitset()
    b2.set_words(b.words())
    assert b2.test(64) and b2.count() == 3


def test_ring_buffer_drop_oldest():
    r = RingBuffer(3)
    assert r.push(1) is None
    r.push(2)
    r.push(3)
    assert r.push(4) == 1  # evicts oldest, probe-queue semantics
    assert r.items() == [2, 3, 4]
    assert r.peek_oldest() == 2 and r.peek_newest() == 4


# ------------------------------------------------------------------ errors


def test_dferrors_wire_roundtrip():
    e = dferrors.NotFound("peer x missing")
    wire = e.to_wire()
    back = dferrors.DFError.from_wire(wire)
    assert isinstance(back, dferrors.NotFound)
    assert back.message == "peer x missing"
    # unknown code degrades to INTERNAL rather than crashing the handler
    odd = dferrors.DFError.from_wire({"code": "SomethingNew", "message": "m"})
    assert odd.code == dferrors.Code.INTERNAL
    # str() reflects the overridden code
    assert str(dferrors.DFError("", code=dferrors.Code.NOT_FOUND)) == "NotFound"


# ---------------------------------------------------------------- dynconfig


def test_dynconfig_poll_cache_fallback(tmp_path):
    calls = {"n": 0, "fail": False}

    def client():
        calls["n"] += 1
        if calls["fail"]:
            raise ConnectionError("manager down")
        return {"schedulers": ["s1"], "v": calls["n"]}

    seen = []
    dc = Dynconfig(client, tmp_path / "dynconfig.json", expire=100.0)
    dc.register(seen.append)
    assert dc.get()["schedulers"] == ["s1"]
    assert dc.get()["v"] == 1  # cached, no second fetch
    assert calls["n"] == 1
    assert seen and seen[0]["v"] == 1

    calls["fail"] = True
    assert dc.refresh()["v"] == 1  # disk fallback serves the last snapshot

    # a fresh instance with a dead source still comes up from disk, and its
    # observers hear about the fallback config too
    dc2 = Dynconfig(client, tmp_path / "dynconfig.json", expire=100.0)
    seen2 = []
    dc2.register(seen2.append)
    assert dc2.get()["v"] == 1
    assert seen2 and seen2[0]["v"] == 1


def test_dynconfig_no_cache_raises(tmp_path):
    def dead():
        raise ConnectionError("down")

    dc = Dynconfig(dead, tmp_path / "none.json", expire=1.0)
    with pytest.raises(dferrors.Unavailable):
        dc.get()


# ------------------------------------------------------------------ plugins


def test_plugin_load(tmp_path):
    (tmp_path / "df_evaluator_plugin_custom.py").write_text(
        "def dragonfly_plugin_init(options):\n"
        "    return {'name': 'custom', 'opts': options}\n"
    )
    p = plugins.load(tmp_path, "evaluator", "custom", {"w": 2})
    assert p == {"name": "custom", "opts": {"w": 2}}
    import sys

    assert "df_evaluator_plugin_custom" in sys.modules  # picklable classes
    with pytest.raises(FileNotFoundError):
        plugins.load(tmp_path, "searcher", "missing")
    with pytest.raises(ValueError):
        plugins.load(tmp_path, "nonsense-type", "x")


# ---------------------------------------------------------- dfpath + dflog


def test_dfpath_layout(tmp_path, monkeypatch):
    monkeypatch.setenv("DRAGONFLY_TPU_HOME", str(tmp_path))
    paths = dfpath.new_paths("scheduler")
    assert paths.work_home == tmp_path / "scheduler"
    for p in (paths.cache_dir, paths.log_dir, paths.data_dir, paths.plugin_dir):
        assert p.is_dir()
    assert paths.lock_file("daemon").name == "daemon.lock"


def test_dflog_scoped(tmp_path, caplog):
    from dragonfly2_tpu.utils import dflog

    dflog.init_logging(tmp_path, console=False)
    log = dflog.with_scope(dflog.get("core"), task_id="t1", peer_id="p1")
    with caplog.at_level(logging.INFO, logger="dragonfly2_tpu.core"):
        log.info("hello")
    assert "[task_id=t1 peer_id=p1] hello" in caplog.text


def test_hoststat_collects_real_numbers():
    """utils/hoststat reads /proc: totals and percents must be live values,
    not zero-filled defaults (announcer.go:186-252 parity)."""
    from dragonfly2_tpu.utils import hoststat

    stats = hoststat.collect("/")
    assert stats.cpu.logical_count > 0
    assert stats.cpu.physical_count > 0
    assert stats.memory.total > 0
    assert 0 < stats.memory.used <= stats.memory.total
    assert 0.0 < stats.memory.used_percent < 100.0
    assert stats.disk.total > 0
    assert stats.disk.inodes_total > 0
    assert stats.tcp_connection_count >= 0
    # second sample after some work yields a cpu percent in range
    deadline = sum(i * i for i in range(200_000))  # burn a little cpu
    assert deadline > 0
    s2 = hoststat.collect("/")
    assert 0.0 <= s2.cpu.percent <= 100.0
    assert s2.cpu.process_percent >= 0.0
