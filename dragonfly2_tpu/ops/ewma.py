"""Probe RTT ring buffers + folded EWMA — device-resident network topology.

Replaces the reference's Redis probe lists (`probes:src:dst` RPUSH/LPOP,
queue length 5) and its moving-average recomputation on every enqueue
(scheduler/networktopology/probes.go:145-221): avg starts at the oldest
probe and folds `avg = W*avg + (1-W)*sample` over the queue in order, with
W = 0.1 (probes.go:39). Here the whole pair set is a (N, Q) ring-buffer
array updated by one scattered device call per probe batch.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from dragonfly2_tpu.config.constants import CONSTANTS

W = CONSTANTS.EWMA_WEIGHT  # weight on the running average


def _ordered(ring: jax.Array, cursor: jax.Array, count: jax.Array) -> jax.Array:
    """Return ring contents oldest->newest along the last axis."""
    q = ring.shape[-1]
    idx = jnp.arange(q, dtype=jnp.int32)
    start = jnp.where(count[..., None] >= q, cursor[..., None], 0)
    gather = (start + idx) % q
    return jnp.take_along_axis(ring, gather, axis=-1)


def fold_average(ring: jax.Array, cursor: jax.Array, count: jax.Array) -> jax.Array:
    """Folded moving average over each pair's queue (probes.go:175-200).

    Empty queues yield 0. Q is static (default 5) so the fold unrolls.
    """
    ordered = _ordered(ring, cursor, count)
    q = ring.shape[-1]
    avg = ordered[..., 0]
    for i in range(1, q):
        has = count > i
        avg = jnp.where(has, W * avg + (1.0 - W) * ordered[..., i], avg)
    return jnp.where(count > 0, avg, 0.0)


@jax.jit
def enqueue(
    ring: jax.Array,       # (N, Q) float32 rtt ns
    cursor: jax.Array,     # (N,) int32 next write slot
    count: jax.Array,      # (N,) int32 valid entries
    pair_idx: jax.Array,   # (M,) int32 pairs receiving a new probe
    rtt_ns: jax.Array,     # (M,) float32
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Scatter M new probes into their pair rings, drop the oldest where
    full, and return recomputed averages for ALL pairs.

    Duplicate pair ids within one batch keep the last write (scatter
    semantics); callers batch at most one probe per pair per tick.
    """
    write_slot = cursor[pair_idx]
    ring = ring.at[pair_idx, write_slot].set(rtt_ns)
    q = ring.shape[-1]
    cursor = cursor.at[pair_idx].set((write_slot + 1) % q)
    count = count.at[pair_idx].set(jnp.minimum(count[pair_idx] + 1, q))
    avg = fold_average(ring, cursor, count)
    return ring, cursor, count, avg


@jax.jit
def probed_count_increment(probed_count: jax.Array, host_idx: jax.Array) -> jax.Array:
    """INCR probed-count:host for each probed destination (probes.go:214-218)."""
    ones = jnp.ones(host_idx.shape, probed_count.dtype)
    return probed_count.at[host_idx].add(ones)


@functools.partial(jax.jit, static_argnames=("k",))
def least_probed_hosts(
    probed_count: jax.Array, alive: jax.Array, noise_key: jax.Array,
    k: int = CONSTANTS.FIND_PROBED_HOSTS_LIMIT,
) -> tuple[jax.Array, jax.Array]:
    """Pick up to k alive hosts, least-probed first with random tie-break —
    FindProbedHosts semantics (networktopology/network_topology.go:190-257)."""
    n = probed_count.shape[0]
    jitter = jax.random.uniform(noise_key, (n,), minval=0.0, maxval=0.5)
    keys = jnp.where(alive, probed_count.astype(jnp.float32) + jitter, jnp.inf)
    _, idx = jax.lax.top_k(-keys, k)
    valid = jnp.take(alive, idx)
    return idx, valid
