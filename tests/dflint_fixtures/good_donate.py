"""dflint green fixture: legal donation idioms. All silent.

Fresh buffer per donating call, the trainer's rebind idiom (donated
args immediately rebound from the return), and mutually-exclusive
if/else branches each donating the same staging buffer once.
"""

import functools

import jax

from dragonfly2_tpu.cluster.scheduler import _EVAL_BUCKETS
from dragonfly2_tpu.ops import evaluator as ev


def fresh_buffer_per_call(fd, k, c, l, n):
    outs = []
    for bsz in _EVAL_BUCKETS:
        buf = ev.pack_eval_batch(fd)  # fresh per donation
        outs.append(ev.schedule_from_packed(buf, bsz, k, c, l, n))
    return outs


@functools.partial(jax.jit, donate_argnums=(0, 1))
def run_epoch(params, opt_state, batches):
    return params, opt_state, batches


def rebind_epoch(params, opt_state, batches):
    # donated args rebound by the same statement: donation is killed
    params, opt_state, losses = run_epoch(params, opt_state, batches)
    return params, opt_state, losses


def branch_exclusive(fd, use_ml, mle, k, c, l, n):
    buf = ev.pack_eval_batch(fd)
    if use_ml:
        out = mle.schedule_from_packed(buf, 64, k, c, l, n)
    else:
        out = ev.schedule_from_packed(buf, 64, k, c, l, n)
    return out
