"""Columnar control plane (PR 8): batched piece-report absorption,
grouped DAG edge application, vectorised candidate fill degenerate
shapes, and the full-tick round-trip smoke.

The per-peer loop implementations remain in-tree as oracles
(state.record_piece, dag.add_edges_from, scheduler vectorized_control=
False); every batch op here is pinned column-for-column or
decision-for-decision against its oracle.
"""

from __future__ import annotations

import numpy as np
import pytest

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.cluster.scheduler import SchedulerService
from dragonfly2_tpu.cluster.simulator import ClusterSimulator
from dragonfly2_tpu.config.config import Config
from dragonfly2_tpu.graph.dag import TaskDAG
from dragonfly2_tpu.state.cluster import ClusterState
from dragonfly2_tpu.state.fsm import PeerState
from dragonfly2_tpu.telemetry.flight import jit_wrappers


def host(i, host_type="normal", idc="idc-a"):
    return msg.HostInfo(
        host_id=f"h-{i}", hostname=f"n-{i}", ip=f"10.0.0.{i}",
        host_type=host_type, idc=idc, concurrent_upload_limit=50,
    )


def register(svc, peer_id, task_id, h, pieces=4):
    return svc.register_peer(msg.RegisterPeerRequest(
        peer_id=peer_id, task_id=task_id, host=h,
        url=f"https://e.com/{task_id}", content_length=pieces * (1 << 20),
        piece_length=1 << 20, total_piece_count=pieces,
    ))


def make_parent(svc, peer_id, task_id, h, pieces=4):
    register(svc, peer_id, task_id, h, pieces)
    svc.handle(msg.DownloadPeerBackToSourceStartedRequest(peer_id=peer_id))
    svc.handle(msg.DownloadPeerBackToSourceFinishedRequest(
        peer_id=peer_id, piece_count=pieces))


# ------------------------------------------- record_pieces_batch oracle


def test_record_pieces_batch_matches_sequential_record_piece():
    """Fuzz: the vectorised batch leaves every column exactly where the
    per-report path does — duplicate pieces, interleaved peers, ring
    wraps (more reports than the ring holds) included."""
    rng = np.random.default_rng(11)
    for trial in range(8):
        a = ClusterState(max_hosts=8, max_tasks=4, max_peers=32,
                         piece_cost_capacity=8)
        b = ClusterState(max_hosts=8, max_tasks=4, max_peers=32,
                         piece_cost_capacity=8)
        for st in (a, b):
            st.upsert_host("h", id_hash=1)
            st.upsert_task("t", total_pieces=64)
            for p in range(4):
                st.add_peer(f"p{p}", 0, 0)
        n = int(rng.integers(1, 40))
        peers = rng.integers(0, 4, n)
        pieces = rng.integers(0, 70, n)  # includes > bitset range is fine
        costs = rng.random(n).astype(np.float32) * 1e9
        for i in range(n):
            a.record_piece(int(peers[i]), int(pieces[i]), float(costs[i]))
        newly = b.record_pieces_batch(peers, pieces, costs)
        assert newly == int(a.peer_finished_count[:4].sum())
        np.testing.assert_array_equal(a.peer_finished_bitset, b.peer_finished_bitset)
        np.testing.assert_array_equal(a.peer_finished_count, b.peer_finished_count)
        np.testing.assert_array_equal(a.peer_piece_costs, b.peer_piece_costs)
        np.testing.assert_array_equal(a.peer_piece_cost_count, b.peer_piece_cost_count)
        np.testing.assert_array_equal(a.peer_cost_cursor, b.peer_cost_cursor)


# --------------------------------------------- add_edges_grouped oracle


def _random_dag(rng, cap=64, edges=40):
    dag = TaskDAG(cap)
    for v in range(cap):
        if rng.random() < 0.8:
            dag.ensure_vertex(v)
    live = np.flatnonzero(dag.present)
    for _ in range(edges):
        u, v = rng.choice(live, 2)
        if dag.can_add_edge(int(u), int(v)):
            dag.add_edge(int(u), int(v))
    return dag


def _clone(dag):
    c = TaskDAG(dag.capacity)
    c.adj = dag.adj.copy()
    c.present = dag.present.copy()
    c.in_degree = dag.in_degree.copy()
    c.out_degree = dag.out_degree.copy()
    return c


def test_add_edges_grouped_matches_sequential():
    rng = np.random.default_rng(5)
    for trial in range(12):
        dag = _random_dag(rng)
        live = np.flatnonzero(dag.present)
        children = rng.choice(live, size=min(6, live.size), replace=False)
        groups = [
            rng.choice(live, size=int(rng.integers(1, 6)), replace=True)
            .astype(np.int64)
            for _ in children
        ]
        seq = _clone(dag)
        expected = [seq.add_edges_from(g, int(c)) for g, c in zip(groups, children)]
        got = dag.add_edges_grouped(groups, children.astype(np.int64))
        for e, g in zip(expected, got):
            np.testing.assert_array_equal(e, g)
        np.testing.assert_array_equal(seq.adj, dag.adj)
        np.testing.assert_array_equal(seq.in_degree, dag.in_degree)
        np.testing.assert_array_equal(seq.out_degree, dag.out_degree)


def test_add_edges_grouped_cross_child_cycle_rejected():
    """The adversarial staleness case: child c2 is selected as a PARENT
    of child c1 earlier in the same batch; a later pair proposing c1 as
    parent of c2 would close a cycle that the pre-batch legality check
    cannot see. The affected-bitset re-check must reject it, exactly as
    the sequential path does."""
    dag = TaskDAG(64)
    for v in (1, 2, 3):
        dag.ensure_vertex(v)
    seq = _clone(dag)
    groups = [np.asarray([2], np.int64), np.asarray([1], np.int64)]
    children = np.asarray([1, 2], np.int64)
    expected = [seq.add_edges_from(g, int(c)) for g, c in zip(groups, children)]
    got = dag.add_edges_grouped(groups, children)
    for e, g in zip(expected, got):
        np.testing.assert_array_equal(e, g)
    assert got[0].tolist() == [True]   # 2 -> 1 lands
    assert got[1].tolist() == [False]  # 1 -> 2 would close the cycle
    np.testing.assert_array_equal(seq.adj, dag.adj)


def test_add_edges_grouped_descendant_cycle_rejected():
    """Deeper variant: the earlier-edged child has a descendant chain;
    proposing a vertex from that chain as a later child's parent must
    trigger the re-check through the descendants bitset."""
    dag = TaskDAG(64)
    for v in (1, 2, 3, 4):
        dag.ensure_vertex(v)
    dag.add_edge(2, 3)  # 2 -> 3 pre-batch: 3 is a descendant of 2
    dag.add_edge(3, 4)
    seq = _clone(dag)
    # batch: child 2 takes parent 1 (edge 1->2); then child 1 proposes
    # parent 4 (4 is now reachable from 1 via 1->2->3->4 => cycle)
    groups = [np.asarray([1], np.int64), np.asarray([4], np.int64)]
    children = np.asarray([2, 1], np.int64)
    expected = [seq.add_edges_from(g, int(c)) for g, c in zip(groups, children)]
    got = dag.add_edges_grouped(groups, children)
    for e, g in zip(expected, got):
        np.testing.assert_array_equal(e, g)
    assert got[1].tolist() == [False]
    np.testing.assert_array_equal(seq.adj, dag.adj)


# -------------------------------------- buffered report ingest oracle


def test_batch_ingest_matches_per_report_path():
    """pieces_finished_batch + flush leaves the scheduler exactly where
    per-report piece_finished calls + flush do: SoA columns, parent host
    upload counters, serving-edge accumulator, dirty frontier, and the
    capped per-parent DownloadRecord stats."""

    def build():
        svc = SchedulerService()
        svc.announce_host(host(0, "super"))
        make_parent(svc, "parent-1", "t-1", host(0), pieces=8)
        make_parent(svc, "parent-2", "t-1", host(1), pieces=8)
        register(svc, "child-1", "t-1", host(2), pieces=8)
        svc.tick()
        return svc

    a, b = build(), build()
    reports = [
        (piece, 1 << 20, (piece + 1) * 1_000_000, "parent-1" if piece % 2 else "parent-2")
        for piece in range(14)  # dups beyond total: 14 reports, 8 pieces
    ]
    for piece, length, cost, parent in reports:
        a.piece_finished(msg.DownloadPieceFinishedRequest(
            peer_id="child-1", piece_number=piece % 8, length=length,
            cost_ns=cost, parent_peer_id=parent,
        ))
    a.flush_piece_reports()
    b.pieces_finished_batch(
        "child-1",
        [p % 8 for p, _, _, _ in reports],
        [length for _, length, _, _ in reports],
        [cost for _, _, cost, _ in reports],
        parent_ids=["parent-2", "parent-1"],
        parent_sel=[p % 2 for p, _, _, _ in reports],
    )
    b.flush_piece_reports()

    ia, ib = a.state.peer_index("child-1"), b.state.peer_index("child-1")
    np.testing.assert_array_equal(
        a.state.peer_finished_bitset[ia], b.state.peer_finished_bitset[ib])
    assert a.state.peer_finished_count[ia] == b.state.peer_finished_count[ib] == 8
    np.testing.assert_array_equal(
        a.state.peer_piece_costs[ia], b.state.peer_piece_costs[ib])
    np.testing.assert_array_equal(a.state.host_upload_count, b.state.host_upload_count)
    # serving edges merged identically (keys include slot generations)
    ea = {k: tuple(v) for k, v in a._serving_edges.items()}
    eb = {k: tuple(v) for k, v in b._serving_edges.items()}
    assert ea == eb and ea
    assert a._dirty_host_slots == b._dirty_host_slots
    ma, mb = a._peer_meta["child-1"], b._peer_meta["child-1"]
    assert set(ma.parents) == set(mb.parents)
    for pid in ma.parents:
        assert ma.parents[pid]["bytes"] == mb.parents[pid]["bytes"]
        assert len(ma.parents[pid]["pieces"]) == len(mb.parents[pid]["pieces"])
        assert [p.cost for p in ma.parents[pid]["pieces"]] == \
            [p.cost for p in mb.parents[pid]["pieces"]]


def test_buffered_reports_survive_parent_leave():
    """A buffered report must absorb into the rows that were live when it
    was enqueued — leaving a peer flushes first, so a recycled row can
    never be credited with a stale report."""
    svc = SchedulerService()
    svc.announce_host(host(0, "super"))
    make_parent(svc, "parent-1", "t-1", host(0))
    register(svc, "child-1", "t-1", host(1))
    svc.tick()
    svc.piece_finished(msg.DownloadPieceFinishedRequest(
        peer_id="child-1", piece_number=0, length=1 << 20,
        cost_ns=1_000_000, parent_peer_id="parent-1",
    ))
    parent_host_slot = int(svc.state.peer_host[svc.state.peer_index("parent-1")])
    svc.leave_peer("parent-1")  # flush valve runs before the row frees
    idx = svc.state.peer_index("child-1")
    assert svc.state.peer_finished_count[idx] == 1
    assert not svc._piece_buf
    # the parent's HOST was credited during the leave's flush (host
    # columns outlive the peer row)
    assert int(svc.state.host_upload_count[parent_host_slot]) >= 1


# --------------------------------------------- degenerate tick shapes


def _signatures():
    w = jit_wrappers().get("scheduler.evaluator.schedule_from_packed")
    return w.stats()["signatures"] if w is not None else 0


def test_tick_zero_pending():
    svc = SchedulerService()
    assert svc.tick() == []


def test_tick_all_candidates_quarantined():
    svc = SchedulerService()
    svc.announce_host(host(0, "super"))
    make_parent(svc, "parent-1", "t-1", host(1))
    register(svc, "child-1", "t-1", host(2))
    svc.quarantine.report("h-1", reason="corruption")
    assert svc.quarantine.is_quarantined("h-1")
    responses = svc.tick()
    # no parent to hand out: the child stays pending (retry loop)
    assert not any(isinstance(r, msg.NormalTaskResponse) for r in responses)
    assert "child-1" in svc._pending


def test_tick_single_host_cluster():
    """Every peer on ONE host: the evaluator filters same-host parents
    (scheduling.go filter semantics), so the degenerate single-host
    cluster must tick without raising and keep the child pending — the
    columnar fill's masks and compaction all see an all-filtered row."""
    svc = SchedulerService()
    h = host(0, "super")
    make_parent(svc, "parent-1", "t-1", h)
    register(svc, "child-1", "t-1", h)
    for _ in range(3):
        responses = svc.tick()
        assert not any(isinstance(r, msg.NormalTaskResponse) for r in responses)
    assert "child-1" in svc._pending  # retry loop, not a crash


def test_tick_slot_recycle_mid_tick():
    """A DAG slot freed and re-registered between ticks: the slot->row
    column must follow the recycle, and the tick schedules the NEW
    occupant without stale-row artifacts or new jit signatures."""
    svc = SchedulerService()
    svc.announce_host(host(0, "super"))
    make_parent(svc, "parent-1", "t-1", host(0))
    register(svc, "child-1", "t-1", host(1))
    svc.tick()
    before = _signatures()
    slot = svc._peer_meta["child-1"].dag_slot
    svc.leave_peer("child-1")
    register(svc, "child-2", "t-1", host(2))
    assert svc._peer_meta["child-2"].dag_slot == slot  # recycled
    spx = svc._slot_pidx["t-1"]
    assert spx[slot] == svc.state.peer_index("child-2")
    responses = svc.tick()
    got = [r for r in responses if isinstance(r, msg.NormalTaskResponse)]
    assert got and got[0].peer_id == "child-2"
    assert all(p.peer_id != "child-1" for p in got[0].candidate_parents)
    assert _signatures() == before  # bucketed shapes: no new compiles


# ------------------------------------------------- full-tick round-trip


def test_columnar_state_round_trips_full_simulated_tick():
    """Tier-1 smoke for the columnar control plane: a simulated round
    (register -> sample/fill -> device select -> batched apply -> batched
    report ingest -> complete) leaves the SoA columns consistent with the
    simulator's own ground truth."""
    cfg = Config()
    svc = SchedulerService(config=cfg, seed=1)
    sim = ClusterSimulator(svc, num_hosts=24, num_tasks=4, seed=1,
                           deterministic_peer_ids=True)
    for _ in range(6):
        sim.run_round(new_downloads=4)
    svc.flush_piece_reports()
    st = svc.state
    assert sim.stats.completed > 0 and sim.stats.pieces > 0
    # every registered, still-live peer's columns agree with its id maps
    for pid, meta in svc._peer_meta.items():
        idx = st.peer_index(pid)
        assert idx is not None and st.peer_alive[idx]
        assert st._peer_id[idx] == pid
        assert svc._dag_slot_peer[meta.task_id][meta.dag_slot] == pid
        assert svc._slot_pidx[meta.task_id][meta.dag_slot] == idx
    # finished bitset popcount == finished count, for every live peer
    live = np.flatnonzero(st.peer_alive)
    bits = st.peer_finished_bitset[live]
    pop = np.zeros(live.size, np.int64)
    for w in range(bits.shape[1]):
        col = bits[:, w]
        while col.any():
            pop += (col & np.uint64(1)).astype(np.int64)
            col = col >> np.uint64(1)
    np.testing.assert_array_equal(pop, st.peer_finished_count[live])
    # every piece the simulator observed flowing is in some peer's bitset
    # (back-to-source/seed completions legitimately hold zero bits — the
    # origin fetch reports no per-piece transfers in this replay)
    assert int(st.peer_finished_count[live].sum()) > 0
    # upload slots in use never exceed limits and return to zero when
    # every download has completed and the buffer is empty
    assert (st.host_upload_used >= 0).all()
    assert not svc._piece_buf
