"""Driver contract: entry() compiles single-device; dryrun_multichip runs a
fully sharded train step on the virtual 8-device mesh."""

import sys
import pathlib

import jax
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import __graft_entry__ as graft  # noqa: E402


def test_entry_compiles_and_runs():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (16, 8)
    assert jax.numpy.isfinite(out).all()


# slow: each dryrun compiles the full sharded train-step/kernel zoo on a
# virtual 8-CPU-device mesh (~3 min together), which alone blows most of
# the tier-1 suite's wall budget. The same entry point runs on every
# driver round as its own multichip leg (MULTICHIP_r{N}.json), so the
# fast tier losing these two adds no coverage gap. (They were red from
# PR 3 to PR 4 for a different reason — jax.config.update
# jax_num_cpu_devices raising AttributeError on jax 0.4.x — fixed in
# __graft_entry__.dryrun_multichip.)


@pytest.mark.slow
def test_dryrun_multichip_8():
    graft.dryrun_multichip(8)


@pytest.mark.slow
def test_dryrun_multichip_odd():
    graft.dryrun_multichip(3)  # graph axis falls back to 1
