"""Streaming SLO engine: declarative objectives over sliding good/bad
counters, multi-window multi-burn-rate alerting, and the health verdict
plane.

The perf observatory (timelines, cost cards) and the decision ledger
measure; nothing JUDGES. This module closes that gap with the Google SRE
workbook construction the reference operates under its Prometheus
alerting rules in ``deploy/``:

- :class:`SLOSpec` — one declarative objective: which SLI stream feeds
  it, the target good-event fraction, the error-budget accounting
  window, and its burn-rate alert rules (default: the fast-page 5m/1h
  pair at 14.4x budget burn and the slow-ticket 30m/6h pair at 6x).
- :class:`SLOEngine` — sliding good/bad event counters per SLO over a
  caller-supplied clock: the EVENT clock in megascale/scenario replays
  (bit-deterministic — same spec + seed, identical alert timelines) and
  the wall clock (``perf_counter`` minutes) in live services. A
  burn-rate alert fires only while BOTH its windows burn above the rule
  factor, so it pages fast on a real spike and clears as soon as the
  short window drains — the multi-window property that bounds alert
  reset time without sacrificing detection.
- the verdict plane: every engine folds its firing alerts into a
  three-state verdict (``ok`` / ``degraded`` / ``critical``) with the
  firing alerts as machine-readable causes; :func:`health_verdict`
  merges every live engine in the process for the ``/debug/health``
  route on the mux and monitor surfaces, the ``slo`` section of
  ``flight.dump()``, and the ``dragonfly_slo_*`` metric families.
- :func:`feed_megascale_sample` / :func:`replay_timeline` — SLI
  derivation from a megascale timeline sample is a PURE function of the
  sample, so ``tools/dfslo.py`` can replay any checked-in timeline or
  BENCH_mega artifact offline and answer "would this run have paged?"
  with the exact alert log the live run produced.

Determinism contract (dflint DET domain): no wall-clock reads anywhere
in this module — callers stamp time. ``perf_counter`` is the one exempt
clock (live engines use it for window arithmetic, never for deciding
replay outcomes).
"""

from __future__ import annotations

import dataclasses
import json
import threading
import weakref
from collections import deque
from typing import Any, Iterable, Mapping

SEVERITY_PAGE = "page"
SEVERITY_TICKET = "ticket"

VERDICT_OK = "ok"
VERDICT_DEGRADED = "degraded"
VERDICT_CRITICAL = "critical"
VERDICT_CODES = {VERDICT_OK: 0, VERDICT_DEGRADED: 1, VERDICT_CRITICAL: 2}
VERDICT_NAMES = {code: name for name, code in VERDICT_CODES.items()}


@dataclasses.dataclass(frozen=True)
class BurnRateRule:
    """One multi-window burn-rate alert rule.

    Fires while the error-budget burn rate over BOTH windows is at or
    above ``factor`` (burn rate 1.0 = consuming exactly the budget).
    The long window gives detection confidence, the short window makes
    the alert clear quickly once the spike passes — reset time is
    bounded by ``short_minutes``, not ``long_minutes``."""

    name: str
    severity: str  # SEVERITY_PAGE | SEVERITY_TICKET
    long_minutes: float
    short_minutes: float
    factor: float


# The SRE-workbook standard pairs: page on a fast burn (14.4x budget
# over 1h+5m — a day's budget in 100 minutes), ticket on a slow burn
# (6x over 6h+30m).
DEFAULT_BURN_RULES: tuple[BurnRateRule, ...] = (
    BurnRateRule("fast_burn", SEVERITY_PAGE, 60.0, 5.0, 14.4),
    BurnRateRule("slow_burn", SEVERITY_TICKET, 360.0, 30.0, 6.0),
)

# Rules for LENIENT objectives (budget near 0.5, e.g. "no open breakers
# most of the time"): burn rate is bounded by 1/budget, so the standard
# 14.4x/6x factors are unreachable there — these fire on SUSTAINED
# near-total badness instead (error ~90% of intervals pages, ~60%
# tickets), same window pairs.
SUSTAINED_BURN_RULES: tuple[BurnRateRule, ...] = (
    BurnRateRule("sustained_page", SEVERITY_PAGE, 60.0, 5.0, 1.8),
    BurnRateRule("sustained_ticket", SEVERITY_TICKET, 360.0, 30.0, 1.2),
)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative objective over a good/bad SLI event stream."""

    name: str
    sli: str
    objective: float  # target good fraction in (0, 1)
    description: str = ""
    window_minutes: float = 24.0 * 60.0  # error-budget accounting window
    burn_rules: tuple[BurnRateRule, ...] = DEFAULT_BURN_RULES
    # abstain below this many events in a rule's long window: one bad
    # event in an otherwise-empty window is noise, not a page
    min_events: int = 8

    def __post_init__(self) -> None:
        if not 0.0 < self.objective < 1.0:
            raise ValueError(
                f"SLO {self.name!r}: objective must be in (0, 1), "
                f"got {self.objective}"
            )
        if self.window_minutes <= 0:
            raise ValueError(f"SLO {self.name!r}: window_minutes must be > 0")

    @property
    def budget(self) -> float:
        """Allowed bad-event fraction (1 - objective)."""
        return 1.0 - self.objective

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "sli": self.sli,
            "objective": self.objective,
            "description": self.description,
            "window_minutes": self.window_minutes,
            "min_events": self.min_events,
            "burn_rules": [dataclasses.asdict(r) for r in self.burn_rules],
        }


class _SlidingCounter:
    """Bucketed good/bad counts over a monotone clock in minutes.

    ``observe`` accumulates into the bucket holding ``t``; buckets older
    than ``max_minutes`` prune on append, so memory is bounded by
    ``max_minutes / bucket_minutes``. ``totals(window, now)`` sums the
    buckets younger than the window (clamped to at least one bucket, so
    a 5-minute alert window still reads the current 15-minute megascale
    round instead of nothing). Deterministic: pure arithmetic over the
    caller's clock."""

    __slots__ = ("bucket_minutes", "max_minutes", "_buckets")

    def __init__(self, bucket_minutes: float, max_minutes: float) -> None:
        self.bucket_minutes = max(bucket_minutes, 1e-6)
        self.max_minutes = max_minutes
        # each entry: [bucket_start_minute, good, bad]
        self._buckets: deque[list[float]] = deque()

    def observe(self, t_minutes: float, good: float, bad: float) -> None:
        start = (t_minutes // self.bucket_minutes) * self.bucket_minutes
        buckets = self._buckets
        if buckets and buckets[-1][0] == start:
            buckets[-1][1] += good
            buckets[-1][2] += bad
        else:
            buckets.append([start, good, bad])
        horizon = t_minutes - self.max_minutes
        while buckets and buckets[0][0] < horizon:
            buckets.popleft()

    def totals(self, window_minutes: float, now_minutes: float) -> tuple[float, float]:
        window = max(window_minutes, self.bucket_minutes)
        cutoff = now_minutes - window
        good = bad = 0.0
        for start, g, b in reversed(self._buckets):
            if start <= cutoff:
                break
            good += g
            bad += b
        return good, bad


@dataclasses.dataclass
class _AlertState:
    firing: bool = False
    fired_t: float | None = None
    fired_count: int = 0


class SLOEngine:
    """Streaming evaluator for a set of :class:`SLOSpec`.

    Usage: ``observe(sli, good=, bad=)`` any number of times per
    interval, then ``step(t)`` once to close the interval at clock
    ``t`` (in caller units; ``minutes_per_unit`` converts — rounds on
    the megascale event clock, minutes on the wall clock). ``step``
    evaluates every objective, runs the burn-rate alert state machines,
    mirrors the results into the ``dragonfly_slo_*`` families, and
    returns the verdict columns for the caller's timeline sample."""

    def __init__(
        self,
        specs: Iterable[SLOSpec],
        name: str | None = None,
        minutes_per_unit: float = 1.0,
        bucket_minutes: float | None = None,
        registry: Any = None,
        alert_log_limit: int = 1024,
    ) -> None:
        specs = tuple(specs)
        seen: dict[str, SLOSpec] = {}
        for spec in specs:
            if spec.name in seen:
                raise ValueError(f"duplicate SLO name {spec.name!r}")
            seen[spec.name] = spec
        self.specs: dict[str, SLOSpec] = seen
        self.name = name or "slo"
        self.minutes_per_unit = minutes_per_unit
        bucket = bucket_minutes if bucket_minutes is not None else minutes_per_unit
        self._mu = threading.Lock()
        self._counters: dict[str, _SlidingCounter] = {}
        self._specs_by_sli: dict[str, list[SLOSpec]] = {}
        for spec in specs:
            longest = max(
                [spec.window_minutes]
                + [r.long_minutes for r in spec.burn_rules]
            )
            self._counters[spec.name] = _SlidingCounter(bucket, longest)
            self._specs_by_sli.setdefault(spec.sli, []).append(spec)
        self._pending: dict[str, list[float]] = {}
        self._alerts: dict[tuple[str, str], _AlertState] = {
            (spec.name, rule.name): _AlertState()
            for spec in specs
            for rule in spec.burn_rules
        }
        self.alert_log: deque[dict] = deque(maxlen=alert_log_limit)
        self.pages_fired = 0
        self.tickets_fired = 0
        self._last_eval: dict[str, dict] = {}
        self._last_t: float | None = None
        # cause enrichment from the tail plane (telemetry/tailtrace.py):
        # the lifecycle phase dominating the interval's completions —
        # set per-sample so a firing TTC page can NAME what it burns on
        self._tail_hint: str | None = None
        from dragonfly2_tpu.telemetry import metrics as _metrics
        from dragonfly2_tpu.telemetry.series import slo_series

        reg = registry if registry is not None else _metrics.default_registry()
        self._series = slo_series(reg)
        self._children: dict[tuple, Any] = {}
        if name is not None:
            register_engine(name, self)

    # ------------------------------------------------------------- feeding

    def set_tail_hint(self, phase: "str | None") -> None:
        """Record the lifecycle phase dominating the current interval's
        completions (tailtrace.round_dominant). TTC-objective causes in
        the next verdict carry it as ``dominant_phase`` — a firing TTC
        page then names WHERE the burn lives. Fed from the timeline
        sample (a pure function of it), so offline replays reproduce the
        identical enriched causes."""
        with self._mu:
            self._tail_hint = phase or None

    def observe(self, sli: str, good: float = 0.0, bad: float = 0.0) -> None:
        """Accumulate good/bad events for ``sli`` into the open interval
        (closed by the next :meth:`step`)."""
        if good == 0.0 and bad == 0.0:
            return
        with self._mu:
            acc = self._pending.setdefault(sli, [0.0, 0.0])
            acc[0] += good
            acc[1] += bad

    def step(self, t: float) -> dict:
        """Close the interval at clock ``t``: stamp pending events,
        evaluate every SLO, run the alert state machines, export
        metrics. Returns the verdict columns (plain scalars plus the
        interval's alert ``transitions``)."""
        now_min = t * self.minutes_per_unit
        with self._mu:
            pending, self._pending = self._pending, {}
            for sli, (good, bad) in pending.items():
                for spec in self._specs_by_sli.get(sli, []):
                    self._counters[spec.name].observe(now_min, good, bad)
                self._export_events(sli, good, bad)
            transitions: list[dict] = []
            evals: dict[str, dict] = {}
            for spec in self.specs.values():
                evals[spec.name] = self._evaluate_locked(
                    spec, now_min, t, transitions
                )
            self._last_eval = evals
            self._last_t = t
            verdict = self._verdict_locked()
            pages, tickets = self.pages_fired, self.tickets_fired
        self._export_verdict(verdict)
        return {
            "verdict": verdict["state"],
            "verdict_code": verdict["state_code"],
            "alerts_firing": len(verdict["causes"]),
            "pages_fired": pages,
            "tickets_fired": tickets,
            "transitions": transitions,
        }

    def _evaluate_locked(
        self, spec: SLOSpec, now_min: float, t: float,
        transitions: list[dict],
    ) -> dict:
        counter = self._counters[spec.name]
        good_w, bad_w = counter.totals(spec.window_minutes, now_min)
        total_w = good_w + bad_w
        error_rate = bad_w / total_w if total_w else 0.0
        allowed = spec.budget * total_w
        budget_remaining = 1.0 - (bad_w / allowed) if allowed > 0 else 1.0
        burns: dict[str, dict] = {}
        for rule in spec.burn_rules:
            g_l, b_l = counter.totals(rule.long_minutes, now_min)
            g_s, b_s = counter.totals(rule.short_minutes, now_min)
            n_l, n_s = g_l + b_l, g_s + b_s
            burn_long = (b_l / n_l) / spec.budget if n_l else 0.0
            burn_short = (b_s / n_s) / spec.budget if n_s else 0.0
            firing = (
                n_l >= spec.min_events
                and burn_long >= rule.factor
                and burn_short >= rule.factor
            )
            state = self._alerts[(spec.name, rule.name)]
            if firing and not state.firing:
                state.firing = True
                state.fired_t = t
                state.fired_count += 1
                if rule.severity == SEVERITY_PAGE:
                    self.pages_fired += 1
                else:
                    self.tickets_fired += 1
                self._child(
                    self._series.alerts_fired, self.name, spec.name,
                    rule.name, rule.severity,
                ).inc()
                event = self._log_transition(
                    t, spec, rule, "fired", burn_long, burn_short
                )
                transitions.append(event)
            elif not firing and state.firing:
                state.firing = False
                event = self._log_transition(
                    t, spec, rule, "cleared", burn_long, burn_short
                )
                transitions.append(event)
            burns[rule.name] = {
                "severity": rule.severity,
                "factor": rule.factor,
                "burn_long": round(burn_long, 4),
                "burn_short": round(burn_short, 4),
                "firing": state.firing,
            }
            self._export_rule(spec, rule, burn_long, burn_short, state.firing)
        self._export_budget(spec, budget_remaining)
        return {
            "sli": spec.sli,
            "objective": spec.objective,
            "events": round(total_w, 3),
            "bad_events": round(bad_w, 3),
            "error_rate": round(error_rate, 6),
            "budget_remaining": round(budget_remaining, 4),
            "burn": burns,
        }

    def _log_transition(
        self, t: float, spec: SLOSpec, rule: BurnRateRule, event: str,
        burn_long: float, burn_short: float,
    ) -> dict:
        entry = {
            "t": t,
            "slo": spec.name,
            "rule": rule.name,
            "severity": rule.severity,
            "event": event,
            "burn_long": round(burn_long, 4),
            "burn_short": round(burn_short, 4),
        }
        self.alert_log.append(entry)
        return entry

    # ------------------------------------------------------------ verdicts

    def _verdict_locked(self) -> dict:
        causes: list[dict] = []
        for (slo_name, rule_name), state in self._alerts.items():
            if not state.firing:
                continue
            spec = self.specs[slo_name]
            rule = next(r for r in spec.burn_rules if r.name == rule_name)
            burn = (self._last_eval.get(slo_name) or {}).get("burn", {})
            cause = {
                "slo": slo_name,
                "rule": rule_name,
                "severity": rule.severity,
                "since_t": state.fired_t,
                **{
                    k: (burn.get(rule_name) or {}).get(k)
                    for k in ("burn_long", "burn_short")
                },
            }
            if slo_name.startswith("ttc") and self._tail_hint:
                # the tail plane's per-interval attribution: the phase a
                # firing TTC objective is actually burning on
                cause["dominant_phase"] = self._tail_hint
            causes.append(cause)
        if any(c["severity"] == SEVERITY_PAGE for c in causes):
            state_name = VERDICT_CRITICAL
        elif causes:
            state_name = VERDICT_DEGRADED
        else:
            state_name = VERDICT_OK
        return {
            "state": state_name,
            "state_code": VERDICT_CODES[state_name],
            "causes": causes,
            "t": self._last_t,
        }

    def verdict(self) -> dict:
        """The engine's current three-state health verdict with its
        firing-alert causes (machine-readable plain data)."""
        with self._mu:
            return self._verdict_locked()

    def dump(self, last_n: int = 128) -> dict:
        """Plain-data snapshot for ``flight.dump()`` / ``/debug/health``
        / bench artifacts: specs, the latest per-SLO evaluation, the
        verdict, counters, and the newest ``last_n`` alert transitions."""
        with self._mu:
            verdict = self._verdict_locked()
            evals = dict(self._last_eval)
            log = list(self.alert_log)
        log = log[-last_n:] if last_n > 0 else []
        return {
            "name": self.name,
            "verdict": verdict,
            "specs": [s.to_dict() for s in self.specs.values()],
            "evaluations": evals,
            "pages_fired": self.pages_fired,
            "tickets_fired": self.tickets_fired,
            "alert_log": log,
        }

    # ------------------------------------------------------------- metrics

    def _child(self, family: Any, *labels: str) -> Any:
        key = (id(family),) + labels
        child = self._children.get(key)
        if child is None:
            child = self._children[key] = family.labels(*labels)
        return child

    def _export_events(self, sli: str, good: float, bad: float) -> None:
        if good:
            self._child(self._series.sli_events, self.name, sli, "good").inc(good)
        if bad:
            self._child(self._series.sli_events, self.name, sli, "bad").inc(bad)

    def _export_rule(
        self, spec: SLOSpec, rule: BurnRateRule,
        burn_long: float, burn_short: float, firing: bool,
    ) -> None:
        s = self._series
        self._child(s.burn_rate, self.name, spec.name, rule.name, "long").set(burn_long)
        self._child(s.burn_rate, self.name, spec.name, rule.name, "short").set(burn_short)
        self._child(
            s.alert_state, self.name, spec.name, rule.name, rule.severity
        ).set(1.0 if firing else 0.0)

    def _export_budget(self, spec: SLOSpec, budget_remaining: float) -> None:
        self._child(
            self._series.budget_remaining, self.name, spec.name
        ).set(budget_remaining)

    def _export_verdict(self, verdict: dict) -> None:
        self._child(self._series.verdict_state, self.name).set(
            float(verdict["state_code"])
        )


# --------------------------------------------------- process-wide registry


_ENGINES: dict[str, "weakref.ref[SLOEngine]"] = {}
_engines_mu = threading.Lock()


def register_engine(name: str, engine: SLOEngine) -> None:
    """Weak named registry (mirrors flight.register_recorder) so the
    process-wide /debug/health and flight.dump surfaces find live SLO
    engines without a handle on their owners. Last registration wins."""
    with _engines_mu:
        _ENGINES[name] = weakref.ref(engine)


def live_engines() -> dict[str, SLOEngine]:
    out: dict[str, SLOEngine] = {}
    with _engines_mu:
        for name, ref in list(_ENGINES.items()):
            eng = ref()
            if eng is None:
                del _ENGINES[name]
            else:
                out[name] = eng
    return out


# ------------------------------------------------------ the verdict plane


# Hard payload bound for the /debug/health routes: the verdict is meant
# for probes and dashboards, not bulk export — far smaller than the
# flight dump's 2 MiB.
HEALTH_MAX_BYTES = 256 << 10


def parse_health_query(query: str) -> dict:
    """``?last_n=&max_bytes=`` → :func:`health_verdict` kwargs — shared
    by the mux and monitor ``/debug/health`` routes (the same contract
    as flight.parse_flight_query). Raises ValueError with a
    client-facing message on bad input (the routes answer 400)."""
    import urllib.parse as _up

    kwargs: dict = {}
    for key, value in _up.parse_qsl(query or ""):
        if key == "last_n":
            try:
                kwargs["last_n"] = max(int(value), 0)
            except ValueError:
                raise ValueError("last_n must be an integer") from None
        elif key == "max_bytes":
            try:
                kwargs["max_bytes"] = max(int(value), 1024)
            except ValueError:
                raise ValueError("max_bytes must be an integer") from None
    return kwargs


def _health_nbytes(body: Mapping[str, Any]) -> int:
    return len(json.dumps(body, separators=(",", ":"), default=str))


def health_verdict(last_n: int = 32,
                   max_bytes: int | None = HEALTH_MAX_BYTES) -> dict:
    """The process health verdict: every live SLO engine's verdict
    merged worst-wins, with firing alerts as causes and the newest
    alert transitions. Plain data; ``max_bytes`` is a hard compact-JSON
    cap enforced by shedding alert-log entries oldest-first (then the
    per-SLO evaluation detail) with a ``truncated`` marker."""
    engines = live_engines()
    worst = VERDICT_OK
    causes: list[dict] = []
    slos: dict[str, dict] = {}
    log: list[dict] = []
    for name in sorted(engines):
        eng = engines[name]
        d = eng.dump(last_n=last_n)
        v = d["verdict"]
        if VERDICT_CODES[v["state"]] > VERDICT_CODES[worst]:
            worst = v["state"]
        for cause in v["causes"]:
            causes.append({"source": name, **cause})
        slos[name] = {
            "state": v["state"],
            "pages_fired": d["pages_fired"],
            "tickets_fired": d["tickets_fired"],
            "evaluations": d["evaluations"],
        }
        # per-source tails, NOT one merged tail-slice: engines run on
        # different clocks (event rounds vs wall minutes), so a global
        # sort or slice would let one engine's backlog displace another
        # engine's newer — possibly currently-firing — transitions.
        # Each engine's dump already bounds its own log to last_n
        # (newest-last); byte growth is bounded by the max_bytes shed.
        for entry in d["alert_log"]:
            log.append({"source": name, **entry})
    body: dict = {
        "state": worst,
        "state_code": VERDICT_CODES[worst],
        "causes": causes,
        "slos": slos,
        "alert_log": log,
        "sources": sorted(engines),
    }
    if max_bytes is not None and _health_nbytes(body) > max_bytes:
        dropped = 0
        while body["alert_log"] and _health_nbytes(body) > max_bytes:
            shed = max(len(body["alert_log"]) // 2, 1)
            dropped += shed
            body["alert_log"] = body["alert_log"][shed:]
            body["truncated"] = {
                "max_bytes": max_bytes, "dropped_alert_log": dropped,
            }
        if _health_nbytes(body) > max_bytes:
            # evaluation detail is the next-largest variable block; the
            # scalar skeleton (state/causes/sources) is the floor
            for entry in body["slos"].values():
                entry.pop("evaluations", None)
            body["truncated"] = {
                "max_bytes": max_bytes, "dropped_alert_log": dropped,
                "dropped_evaluations": True,
            }
    return body


# ----------------------------------------------- megascale SLI derivation


# Per-region time-to-complete tier: an interval whose streaming p95
# exceeds this is a bad TTC interval. Generous against the measured
# planet-day steady state (p50 ~2.2 s, BENCH_mega) so the clean-day
# alert-noise gate holds; the WAN tier model (2103.10515) prices the
# worst in-region path well under it.
MEGASCALE_TTC_P95_MS = 60_000.0


def megascale_slo_specs(regions: Iterable[str]) -> tuple[SLOSpec, ...]:
    """The megascale lab's SLO set, sized against the soak/planet
    builtins: integrity (corruption rate), announce stability
    (scheduler-loss re-announces — the SLI a scheduler kill burns),
    origin offload (the <10% origin-fraction north star), breaker
    census, and one per-region TTC objective."""
    specs = [
        SLOSpec(
            "integrity", sli="integrity", objective=0.995,
            description="pieces free of digest-verified corruption",
        ),
        SLOSpec(
            "announce_stability", sli="announce", objective=0.999,
            description="completions not forced to re-announce by "
                        "scheduler loss",
        ),
        SLOSpec(
            "origin_offload", sli="origin", objective=0.90,
            description="piece traffic served peer-to-peer instead of "
                        "falling back to origin",
        ),
        SLOSpec(
            "breaker_health", sli="breakers", objective=0.5,
            burn_rules=SUSTAINED_BURN_RULES,
            description="evaluation intervals without open circuit "
                        "breakers anywhere in the process",
        ),
    ]
    for region in regions:
        specs.append(SLOSpec(
            f"ttc_{region}", sli=f"ttc_{region}", objective=0.95,
            min_events=4,
            description=f"intervals whose {region} completion-time p95 "
                        f"stays under {MEGASCALE_TTC_P95_MS / 1e3:.0f}s",
        ))
    return tuple(specs)


def feed_megascale_sample(engine: SLOEngine, sample: Mapping[str, Any]) -> dict:
    """Derive every megascale SLI from ONE timeline sample, feed the
    engine, and step it at the sample's event clock. A pure function of
    the sample dict — the engine inside EventBatchEngine and the
    offline :func:`replay_timeline` path MUST produce identical alert
    timelines from identical samples (pinned by tests/test_slo.py)."""
    pieces = int(sample.get("pieces") or 0)
    corruptions = int(sample.get("corruptions") or 0)
    hint = sample.get("tail_dominant_phase")
    engine.set_tail_hint(hint if isinstance(hint, str) else None)
    engine.observe(
        "integrity", good=max(pieces - corruptions, 0), bad=corruptions
    )
    completed = int(sample.get("completed") or 0)
    reannounced = int(sample.get("reannounce_backlog") or 0)
    engine.observe("announce", good=completed, bad=reannounced)
    origin_fraction = float(sample.get("origin_fraction") or 0.0)
    bad_origin = int(round(pieces * origin_fraction))
    engine.observe(
        "origin", good=max(pieces - bad_origin, 0), bad=bad_origin
    )
    open_breakers = int(sample.get("breaker_open") or 0)
    engine.observe(
        "breakers",
        good=0 if open_breakers else 1,
        bad=open_breakers,
    )
    p95_by_region = sample.get("ttc_ms_p95") or {}
    if isinstance(p95_by_region, Mapping):
        for region in sorted(p95_by_region):
            p95 = p95_by_region[region]
            if p95 is None:
                continue
            ok = float(p95) <= MEGASCALE_TTC_P95_MS
            engine.observe(
                f"ttc_{region}", good=1 if ok else 0, bad=0 if ok else 1
            )
    return engine.step(float(sample["t"]))


def replay_timeline(
    samples: Iterable[Mapping[str, Any]],
    minutes_per_unit: float,
    specs: Iterable[SLOSpec] | None = None,
) -> dict:
    """Replay a recorded megascale timeline against an SLO config on a
    FRESH engine (isolated metrics registry — a replay must not clobber
    the live process gauges) and return the full judgment: per-sample
    verdict columns, the alert log, and the page/ticket verdict
    ``tools/dfslo.py`` exits on. Bit-deterministic in the samples."""
    from dragonfly2_tpu.telemetry.metrics import Registry

    samples = list(samples)
    if specs is None:
        regions: list[str] = []
        for s in samples:
            p95 = s.get("ttc_ms_p95")
            if isinstance(p95, Mapping):
                regions = sorted(p95)
                break
        specs = megascale_slo_specs(regions)
    engine = SLOEngine(
        specs, minutes_per_unit=minutes_per_unit, registry=Registry()
    )
    columns: list[dict] = []
    for sample in samples:
        step = feed_megascale_sample(engine, sample)
        columns.append({
            "t": sample["t"],
            "slo_verdict": step["verdict_code"],
            "slo_alerts_firing": step["alerts_firing"],
            "slo_pages_fired": step["pages_fired"],
            "slo_tickets_fired": step["tickets_fired"],
        })
    final = engine.verdict()
    return {
        "samples": columns,
        "alert_log": list(engine.alert_log),
        "pages_fired": engine.pages_fired,
        "tickets_fired": engine.tickets_fired,
        "paged": engine.pages_fired > 0,
        "verdict_final": final["state"],
        "worst_verdict": VERDICT_NAMES[
            max((c["slo_verdict"] for c in columns), default=0)
        ],
        "budget_remaining": {
            name: ev.get("budget_remaining")
            for name, ev in engine.dump()["evaluations"].items()
        },
    }


def slo_report(engine: SLOEngine, last_n: int = 256) -> dict:
    """The flattened SLO block artifact writers consume (megascale soak
    report, bench_megascale summary): deterministic on the event clock."""
    d = engine.dump(last_n=last_n)
    budgets = {
        name: ev.get("budget_remaining")
        for name, ev in d["evaluations"].items()
    }
    finite = [b for b in budgets.values() if isinstance(b, (int, float))]
    return {
        "verdict_final": d["verdict"]["state"],
        "verdict_code_final": d["verdict"]["state_code"],
        "pages_fired": d["pages_fired"],
        "tickets_fired": d["tickets_fired"],
        "alerts_fired": d["pages_fired"] + d["tickets_fired"],
        "budget_remaining": budgets,
        # worst-case budget consumption across SLOs, as a single
        # lower-is-better artifact cell (benchwatch direction tables)
        "budget_burn": round(1.0 - min(finite), 4) if finite else 0.0,
        "alert_log": d["alert_log"],
        "slos": sorted(engine.specs),
    }


# ------------------------------------------------- scheduler (wall clock)


def scheduler_slo_specs(tick_budget_ms: float) -> tuple[SLOSpec, ...]:
    """The live scheduler's SLO set: tick latency against its budget
    (PhaseRecorder is the timing source of record; the SLI counts whole
    ticks over/under budget), shadow regret from the decision ledger
    (disagreement decisions count against the budget only while the
    measured fail-rate regret says the active arm is losing), and the
    process breaker census."""
    return (
        SLOSpec(
            "tick_latency", sli="tick_latency", objective=0.99,
            description=f"scheduler ticks completing within "
                        f"{tick_budget_ms:.0f} ms",
        ),
        SLOSpec(
            "shadow_regret", sli="shadow_regret", objective=0.5,
            burn_rules=SUSTAINED_BURN_RULES,
            description="shadow-scored decisions where the active arm "
                        "is not measurably losing to the inactive arm",
        ),
        SLOSpec(
            "breaker_health", sli="breakers", objective=0.5,
            burn_rules=SUSTAINED_BURN_RULES,
            description="evaluation intervals without open circuit "
                        "breakers anywhere in the process",
        ),
    )
