"""Horizontally-sharded scheduler control plane — K task-sharded
scheduler replicas behind one consistent hashring.

One scheduler process was the last scale wall in the megascale lab: the
columnar SoA scheduler tops out around ~47k pieces/s at 10^5 hosts, and
nothing above it could grow the control plane horizontally. The
reference system shards exactly this way — pkg/balancer consistent
hashing over a scheduler cluster, every request for a task id landing on
the scheduler whose in-memory DAG for that task is authoritative — and
every ingredient already exists in this repo: the hashring with failover
walk (`utils/hashring.py`), partial-download adoption on re-announce
(`RegisterPeerRequest.finished_pieces` → ``state.adopt_pieces``), and
the bulk register/report/leave APIs the event-batch engine drives.

:class:`SchedulerFleet` composes them: K live
:class:`~dragonfly2_tpu.cluster.scheduler.SchedulerService` replicas,
one :class:`~dragonfly2_tpu.utils.hashring.HashRing` over their names,
and a task-affinity router — task-keyed messages (register, seed
trigger, handoff) go to the ring owner, peer-keyed reports follow the
peer's recorded shard, host-plane messages broadcast (every replica
sees every host, as every reference scheduler does via the manager).

Cross-scheduler peer handoff is the new protocol edge: when a replica
crashes, restarts under a rolling upgrade, or rejoins the ring, every
in-flight peer whose task's ring owner moved is released by the old
owner and re-announced to the new one via
:class:`~dragonfly2_tpu.cluster.messages.PeerHandoffRequest` — carrying
the pieces the daemon kept, so the receiving scheduler ADOPTS the
partial download through the same ``finished_pieces`` path a
single-scheduler crash exercises (PR 3), now scheduler-to-scheduler.

:class:`FleetEventBatchEngine` drives a fleet through the megascale lab
with the single-scheduler engine's exact protocol behavior at K=1 (the
equivalence oracle test pins SimStats, the fault digest, and the
tail/decision digests bit-identical), while K>1 adds ring maintenance:
crash victims leave the ring and hand off, upgrade windows roll
replicas gracefully, rejoins rebalance peers back. Determinism contract:
ring-rebalance iteration is SORTED (the handoff order drives the
receiving replica's pending-queue order — the exact class of bug the
simulator's partition paths fixed), and the only clock in this module
is ``perf_counter`` for the per-shard timing ledger that the
modeled-parallel wall accounting reads.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from dragonfly2_tpu.cluster import messages as msg
from dragonfly2_tpu.megascale.engine import EventBatchEngine, megascale_service
from dragonfly2_tpu.utils.hashring import HashRing

HANDOFF_REASONS = ("crash", "upgrade", "rebalance")


class _FleetQuarantineView:
    """Fleet-wide quarantine census: each replica quarantines parents
    independently (its own corruption evidence), the fleet view sums."""

    def __init__(self, fleet: "SchedulerFleet"):
        self._fleet = fleet

    def active_count(self) -> int:
        return sum(r.quarantine.active_count() for r in self._fleet.replicas)


class _FleetRecorderView:
    """Tick-phase view over the replicas' PhaseRecorders: per phase, the
    slowest replica's p50 — the fleet's critical-path tick breakdown
    (replicas tick on separate machines in production, so the max is the
    honest per-phase wall, not the sum)."""

    def __init__(self, fleet: "SchedulerFleet"):
        self._fleet = fleet

    def phase_p50s(self) -> dict:
        merged: dict = {}
        for r in self._fleet.replicas:
            for phase, p50 in r.recorder.phase_p50s().items():
                if merged.get(phase) is None or (
                    p50 is not None and p50 > merged[phase]
                ):
                    merged[phase] = p50
        return merged


class FleetDecisionView:
    """Decision-ledger facade over the replicas' ledgers.

    K=1 returns the single ledger's report and digest VERBATIM — the
    K=1 equivalence oracle compares decision digests bit-for-bit against
    a bare single-scheduler run, so even hashing one digest again would
    break the contract. K>1 merges: counters sum, divergence aggregates
    weight by each replica's compared/disagreement volume, and the
    digest chains the per-replica digests in replica order (replica
    order is construction order — deterministic)."""

    def __init__(self, fleet: "SchedulerFleet"):
        self._fleet = fleet

    def _ledgers(self) -> list:
        return [
            r.decisions for r in self._fleet.replicas
            if r.decisions is not None
        ]

    def counters(self) -> dict:
        out = {
            "decisions": 0, "joined": 0,
            "shadow_compared": 0, "shadow_top1_disagree": 0,
        }
        for led in self._ledgers():
            for key, v in led.counters().items():
                out[key] = out.get(key, 0) + int(v)
        return out

    def report(self) -> dict:
        ledgers = self._ledgers()
        if len(ledgers) == 1:
            return ledgers[0].report()
        reports = [led.report() for led in ledgers]
        out: dict = dict(self.counters())
        compared = [r["shadow_compared"] for r in reports]
        dis = [r["n_disagreements"] for r in reports]

        def wmean(key: str, weights: list, nd: int):
            num = den = 0.0
            for r, w in zip(reports, weights):
                if r.get(key) is not None and w > 0:
                    num += r[key] * w
                    den += w
            return round(num / den, nd) if den else None

        out["top1_disagreement"] = wmean("top1_disagreement", compared, 4)
        out["rank_corr"] = wmean("rank_corr", compared, 4)
        out["n_disagreements"] = sum(dis)
        out["regret_ttc_ms"] = wmean("regret_ttc_ms", dis, 3)
        out["regret_fail_rate"] = wmean("regret_fail_rate", dis, 4)
        by_arm: dict = {}
        for r in reports:
            for arm, e in (r.get("regret_by_arm") or {}).items():
                acc = by_arm.setdefault(arm, {"n": 0, "_ttc": [], "_fail": []})
                acc["n"] += e["n"]
                if e.get("regret_ttc_ms") is not None:
                    acc["_ttc"].append((e["regret_ttc_ms"], max(e["n"], 1)))
                if e.get("regret_fail_rate") is not None:
                    acc["_fail"].append((e["regret_fail_rate"], max(e["n"], 1)))

        def pooled(pairs: list, nd: int):
            den = sum(w for _, w in pairs)
            return (
                round(sum(v * w for v, w in pairs) / den, nd) if den else None
            )

        out["regret_by_arm"] = {
            arm: {
                "n": acc["n"],
                "regret_ttc_ms": pooled(acc["_ttc"], 3),
                "regret_fail_rate": pooled(acc["_fail"], 4),
            }
            for arm, acc in sorted(by_arm.items())
        }
        out["regret_fail_rate_by_arm"] = {
            arm: e["regret_fail_rate"]
            for arm, e in out["regret_by_arm"].items()
        }
        return out

    def deterministic_digest(self) -> str:
        ledgers = self._ledgers()
        if len(ledgers) == 1:
            return ledgers[0].deterministic_digest()
        import hashlib

        h = hashlib.blake2b(digest_size=16)
        for led in ledgers:
            h.update(led.deterministic_digest().encode())
        return h.hexdigest()


class SchedulerFleet:
    """K task-sharded scheduler replicas behind one consistent hashring.

    Capability parity with the reference's scheduler cluster: pkg/
    balancer consistent hashing routes every task to one scheduler whose
    in-memory state for it is authoritative (the dynconfig-fed resolver
    keeps daemons on that affinity), and a replica leaving the ring
    moves its ranges to the survivors. The fleet exposes the same
    surface a single :class:`SchedulerService` does (register/report/
    leave/tick/counts/…) so the simulator and event-batch engine drive
    it unchanged; routing is:

    - task-keyed → ring owner: ``register_peer`` / ``register_peers_
      batch`` / ``PeerHandoffRequest`` / ``trigger_seed_download``;
    - peer-keyed → recorded shard: every piece/peer report, ``leave_
      peer``, ``reschedule`` (a daemon keeps reporting to the scheduler
      that answered its announce);
    - host-plane → broadcast: ``announce_host`` / ``leave_hosts_batch``
      (every reference scheduler learns every host via the manager).

    Every routed call is timed per shard (``perf_counter`` — DET-exempt)
    into a seconds ledger the engine folds into serial vs critical-path
    scheduler time: replicas run on separate machines in production, so
    the per-round max across shards is the honest parallel wall.
    """

    def __init__(self, replicas, names=None, registry=None, vnodes=64):
        if not replicas:
            raise ValueError("SchedulerFleet needs at least one replica")
        self.replicas = list(replicas)
        self.names = (
            list(names) if names is not None
            else [f"scheduler-{k}" for k in range(len(self.replicas))]
        )
        if len(self.names) != len(self.replicas):
            raise ValueError("one name per replica")
        self._shard_of_name = {n: k for k, n in enumerate(self.names)}
        # `vnodes` = virtual nodes per replica on the ring: more vnodes
        # cut the ring into finer bands, so each replica's share of the
        # task catalog tracks 1/K more closely — at the default 64 a
        # 4-replica fleet can own a third of a 256-task catalog on one
        # shard purely from lumpy band boundaries
        self.ring = HashRing(self.names, replicas=vnodes)
        # fleet-level lock: the simulator's seed-trigger drain swap-
        # assigns under `scheduler.mu`; reentrant because routed calls
        # may nest (register inside a drain)
        self.mu = threading.RLock()
        # peer -> shard that answered its announce (the reporting
        # affinity); set at register, moved at handoff, dropped at leave
        self._peer_shard: dict[str, int] = {}
        self._down: set[int] = set()
        self._sched_seconds = [0.0] * len(self.replicas)
        self.pieces_by_shard = [0] * len(self.replicas)
        self.handoffs = {reason: 0 for reason in HANDOFF_REASONS}
        self.restarts = 0
        # the fleet does not model a cluster-wide probe plane (each
        # replica's ProbeStore stays per-shard); the simulator's probe
        # round checks this and no-ops
        self.probes = None
        self.quarantine = _FleetQuarantineView(self)
        self.recorder = _FleetRecorderView(self)
        self._decisions_view = FleetDecisionView(self)
        from dragonfly2_tpu.telemetry import default_registry
        from dragonfly2_tpu.telemetry.series import fleet_series

        series = fleet_series(
            registry if registry is not None else default_registry()
        )
        self._m_handoffs = {
            reason: series.handoffs.labels(reason)
            for reason in HANDOFF_REASONS
        }
        self._m_pieces = [series.shard_pieces.labels(n) for n in self.names]
        self._m_restarts = [
            series.shard_restarts.labels(n) for n in self.names
        ]
        self._m_shards = series.shards_in_ring.labels()
        self._m_shards.set(float(len(self.ring)))

    # ------------------------------------------------------------ routing

    @property
    def k(self) -> int:
        return len(self.replicas)

    @property
    def decisions(self):
        if all(r.decisions is None for r in self.replicas):
            return None
        return self._decisions_view

    def shard_of_task(self, task_id: str) -> int:
        name = self.ring.pick(task_id)
        if name is None:  # whole ring down — degrade to replica 0
            return 0
        return self._shard_of_name[name]

    def shard_of_peer(self, peer_id: str) -> int | None:
        return self._peer_shard.get(peer_id)

    def _timed(self, shard: int, fn, *args, **kwargs):
        t0 = time.perf_counter()
        try:
            return fn(*args, **kwargs)
        finally:
            self._sched_seconds[shard] += time.perf_counter() - t0

    def sched_seconds(self) -> list[float]:
        """Cumulative routed-call seconds per shard (the engine's
        serial/critical-path accounting snapshots deltas per round)."""
        return list(self._sched_seconds)

    # ------------------------------------------------------- task-keyed

    def register_peer(self, req: msg.RegisterPeerRequest):
        with self.mu:
            shard = self.shard_of_task(req.task_id)
            prev = self._peer_shard.get(req.peer_id)
            if prev is not None and prev != shard:
                # the ring moved while this peer was stalled/partitioned:
                # release the old owner's row first so the register below
                # is a clean adoption on the new owner, not a split brain
                self._timed(prev, self.replicas[prev].leave_peer, req.peer_id)
                self._peer_shard.pop(req.peer_id, None)
            resp = self._timed(shard, self.replicas[shard].register_peer, req)
            if isinstance(resp, msg.ScheduleFailure):
                self._peer_shard.pop(req.peer_id, None)
            else:
                self._peer_shard[req.peer_id] = shard
            return resp

    def register_peers_batch(self, reqs) -> list:
        """Bulk register routed per shard: requests group by ring owner
        PRESERVING list order within each shard (slot allocation and
        seed-trigger round-robin are order-dependent), one bulk call per
        shard in ascending shard order, responses reassembled in the
        original request order. K=1 degenerates to exactly one bulk call
        with the untouched list — bit-identical to the bare service."""
        with self.mu:
            by_shard: dict[int, list[int]] = {}
            for i, req in enumerate(reqs):
                by_shard.setdefault(self.shard_of_task(req.task_id), []).append(i)
            out: list = [None] * len(reqs)
            for shard in sorted(by_shard):
                idxs = by_shard[shard]
                resps = self._timed(
                    shard, self.replicas[shard].register_peers_batch,
                    [reqs[i] for i in idxs],
                )
                for i, resp in zip(idxs, resps):
                    out[i] = resp
                    if isinstance(resp, msg.ScheduleFailure):
                        self._peer_shard.pop(reqs[i].peer_id, None)
                    else:
                        self._peer_shard[reqs[i].peer_id] = shard
            return out

    def trigger_seed_download(self, task_id: str, url: str, **kwargs) -> bool:
        shard = self.shard_of_task(task_id)
        return self._timed(
            shard, self.replicas[shard].trigger_seed_download,
            task_id, url, **kwargs,
        )

    # ------------------------------------------------------- peer-keyed

    def _route_peer(self, peer_id: str):
        shard = self._peer_shard.get(peer_id)
        if shard is None:
            return None, None
        return shard, self.replicas[shard]

    def _peer_call(self, method: str, req):
        shard, replica = self._route_peer(getattr(req, "peer_id", ""))
        if replica is None:
            return msg.ScheduleFailure(
                getattr(req, "peer_id", ""), "NotFound",
                "peer unknown to the fleet router",
            )
        return self._timed(shard, getattr(replica, method), req)

    def piece_finished(self, req: msg.DownloadPieceFinishedRequest):
        shard = self._peer_shard.get(req.peer_id)
        if shard is not None:
            self.pieces_by_shard[shard] += 1
            self._m_pieces[shard].inc()
        return self._peer_call("piece_finished", req)

    def pieces_finished_batch(
        self, peer_id, piece_numbers, lengths, costs_ns,
        parent_ids=(), parent_sel=None,
    ):
        shard, replica = self._route_peer(peer_id)
        if replica is None:
            return msg.ScheduleFailure(
                peer_id, "NotFound", "peer unknown to the fleet router"
            )
        n = len(piece_numbers)
        self.pieces_by_shard[shard] += n
        self._m_pieces[shard].inc(n)
        return self._timed(
            shard, replica.pieces_finished_batch,
            peer_id, piece_numbers, lengths, costs_ns,
            parent_ids=parent_ids, parent_sel=parent_sel,
        )

    def piece_failed(self, req):
        return self._peer_call("piece_failed", req)

    def peer_finished(self, req):
        return self._peer_call("peer_finished", req)

    def peer_failed(self, req):
        return self._peer_call("peer_failed", req)

    def back_to_source_started(self, req):
        return self._peer_call("back_to_source_started", req)

    def back_to_source_finished(self, req):
        return self._peer_call("back_to_source_finished", req)

    def back_to_source_failed(self, req):
        return self._peer_call("back_to_source_failed", req)

    def reschedule(self, req):
        return self._peer_call("reschedule", req)

    def leave_peer(self, peer_id: str) -> None:
        with self.mu:
            shard = self._peer_shard.pop(peer_id, None)
            if shard is not None:
                self._timed(shard, self.replicas[shard].leave_peer, peer_id)

    # ---------------------------------------------------------- dispatch

    def handle(self, request):
        """Announce-stream dispatch with fleet routing: handoffs and
        registers route by task ring, every other message follows the
        peer's recorded shard — the wire surface the RPC edge (and the
        skew proxy's N-1 codec round-trip) drives."""
        if isinstance(request, msg.PeerHandoffRequest):
            return self._handle_handoff(request)
        if isinstance(request, msg.RegisterPeerRequest):
            return self.register_peer(request)
        shard, replica = self._route_peer(getattr(request, "peer_id", ""))
        if replica is None:
            return msg.ScheduleFailure(
                getattr(request, "peer_id", ""), "NotFound",
                "peer unknown to the fleet router",
            )
        return self._timed(shard, replica.handle, request)

    def _handle_handoff(self, req: msg.PeerHandoffRequest):
        with self.mu:
            shard = self.shard_of_task(req.task_id)
            reason = req.reason if req.reason in self.handoffs else "rebalance"
            self.handoffs[reason] += 1
            self._m_handoffs[reason].inc()
            resp = self._timed(shard, self.replicas[shard].handle, req)
            if isinstance(resp, msg.ScheduleFailure):
                self._peer_shard.pop(req.peer_id, None)
            else:
                self._peer_shard[req.peer_id] = shard
            return resp

    # ---------------------------------------------------------- host plane

    def announce_host(self, host: msg.HostInfo):
        out = None
        for shard, replica in enumerate(self.replicas):
            out = self._timed(shard, replica.announce_host, host)
        return out

    def leave_hosts_batch(self, host_ids) -> int:
        ids = list(host_ids)
        dropped = 0
        for shard, replica in enumerate(self.replicas):
            dropped = self._timed(shard, replica.leave_hosts_batch, ids)
        return dropped

    def leave_host(self, host_id: str) -> None:
        for shard, replica in enumerate(self.replicas):
            self._timed(shard, replica.leave_host, host_id)

    def apply_dynconfig(self, data: dict) -> None:
        for replica in self.replicas:
            replica.apply_dynconfig(data)

    def warmup(self) -> None:
        for shard, replica in enumerate(self.replicas):
            self._timed(shard, replica.warmup)

    def flush_piece_reports(self) -> int:
        return sum(
            self._timed(shard, replica.flush_piece_reports)
            for shard, replica in enumerate(self.replicas)
        )

    # --------------------------------------------------------------- tick

    def tick(self) -> list:
        """One scheduling round across the fleet: every replica ticks in
        replica order (down replicas tick too — their drained pending
        queues make it a no-op — so rejoin cannot reorder the loop), and
        the responses concatenate in that order. K=1 is the bare
        service's tick, response-for-response."""
        out: list = []
        for shard, replica in enumerate(self.replicas):
            out.extend(self._timed(shard, replica.tick))
        return out

    @property
    def seed_triggers(self) -> list:
        """Fleet-wide seed-trigger queue view in replica order. The
        simulator drains it with a swap-assign under ``mu``; assignment
        routes each trigger back to its task's ring owner (an empty
        assignment — the drain — just clears every replica)."""
        out: list = []
        for replica in self.replicas:
            out.extend(replica.seed_triggers)
        return out

    @seed_triggers.setter
    def seed_triggers(self, value) -> None:
        with self.mu:
            for replica in self.replicas:
                replica.seed_triggers = []
            for trig in value:
                self.replicas[self.shard_of_task(trig.task_id)] \
                    .seed_triggers.append(trig)

    # ------------------------------------------------------ ring lifecycle

    def shard_down(self, shard: int) -> None:
        """Take a replica out of the ring (crash or rolling-upgrade
        restart). Its ranges move to ring successors; the engine hands
        its in-flight peers off. A lone replica restarts in place — a
        K=1 fleet has nowhere to move ownership, which is exactly the
        single-scheduler crash semantics the oracle models."""
        if self.k == 1:
            return
        self.ring.remove(self.names[shard])
        self._down.add(shard)
        self._m_shards.set(float(len(self.ring)))

    def shard_up(self, shard: int) -> None:
        """Re-admit a replica to the ring after a restart."""
        if shard in self._down:
            self._down.discard(shard)
            self.restarts += 1
            self._m_restarts[shard].inc()
        self.ring.add(self.names[shard])
        self._m_shards.set(float(len(self.ring)))

    def down_shards(self) -> list[int]:
        return sorted(self._down)

    # ----------------------------------------------------------- reporting

    def counts(self) -> dict:
        """Entity counts summed across replicas — the same keys a single
        service's ``counts()`` reports, so report consumers are
        layout-compatible. Hosts count K× at K>1 (every replica
        announces every host, as in the reference deployment)."""
        total: dict = {}
        for replica in self.replicas:
            for key, v in replica.counts().items():
                total[key] = total.get(key, 0) + int(v)
        return total

    def counts_by_shard(self) -> dict:
        return {
            name: self.replicas[shard].counts()
            for shard, name in enumerate(self.names)
        }

    def fleet_counters(self) -> dict:
        """Deterministic fleet-plane counters for the megascale report's
        ``fleet`` block."""
        live_by_shard = [0] * self.k
        for shard in self._peer_shard.values():
            live_by_shard[shard] += 1
        return {
            "handoffs": dict(self.handoffs),
            "handoffs_total": sum(self.handoffs.values()),
            "restarts": int(self.restarts),
            "pieces_by_shard": {
                name: int(self.pieces_by_shard[shard])
                for shard, name in enumerate(self.names)
            },
            "routed_peers_by_shard": {
                name: live_by_shard[shard]
                for shard, name in enumerate(self.names)
            },
            "shards_in_ring": len(self.ring),
            "down_shards": self.down_shards(),
        }


class FleetEventBatchEngine(EventBatchEngine):
    """Event-batch engine over a :class:`SchedulerFleet`.

    Single-scheduler protocol behavior is inherited unchanged — at K=1
    every routed call degenerates to the bare service call, so SimStats,
    the fault digest, and the tail/decision digests are bit-identical to
    an :class:`EventBatchEngine` run on paired seeds (the equivalence
    oracle test pins this). K>1 adds the fleet plane:

    - scheduler crashes pick a round-robin victim replica; its pending
      peers are released and handed off to the new ring owners via
      ``PeerHandoffRequest`` (through ``scheduler.handle`` so the skew
      proxy's N-1 codec covers the frame), and the victim leaves the
      ring for ``crash_down_rounds`` rounds;
    - rolling-upgrade windows (scenarios UpgradeSpec) gracefully restart
      the replica whose ring band the host sweep crosses — handoff away,
      one round out, rebalance back;
    - every ring change triggers a SORTED rebalance walk moving
      in-flight peers whose owner moved (kept pieces adopted);
    - the timeline grows per-shard piece columns, handoff deltas and
      ring census; a second TailTrace attributes completion phases per
      shard; per-round scheduler seconds split serial vs critical-path.
    """

    def __init__(self, scheduler, fleet: SchedulerFleet | None = None,
                 crash_down_rounds: int = 2, **kwargs):
        # the driver may be a SkewProxy over the fleet; keep a direct
        # handle for ring lifecycle + counters (the proxy only mediates
        # message-shaped calls)
        self.fleet = fleet if fleet is not None else scheduler
        self._col_shard = np.full(1024, -1, np.int16)
        super().__init__(scheduler, **kwargs)
        self._crash_down_rounds = max(int(crash_down_rounds), 1)
        self._crash_counter = 0
        self._crash_victims: list[tuple[int, int]] = []  # (round, shard)
        self._down_until: dict[int, int] = {}
        self._upgrade_last = [-(1 << 30)] * self.fleet.k
        self._sched_round_s = [0.0] * self.fleet.k
        self._sched_prev = self.fleet.sched_seconds()
        self._tl_prev_shard = [0] * self.fleet.k
        self._tl_prev_handoffs = 0
        from dragonfly2_tpu.telemetry import tailtrace as _tailtrace

        self.tail_shard = _tailtrace.TailTrace(
            list(self.fleet.names),
            seed=kwargs.get("seed", 0),
            name="megascale.fleet.tail",
        )

    # ------------------------------------------------------------ columns

    def _ensure_cols(self, n: int) -> None:
        super()._ensure_cols(n)
        cap = self._col_task.shape[0]
        if self._col_shard.shape[0] < cap:
            grown = np.full(cap, -1, np.int16)
            grown[: self._col_shard.shape[0]] = self._col_shard
            self._col_shard = grown

    def _new_download_request(self, host=None, task=None):
        reg = self._reg_index
        req = super()._new_download_request(host, task)
        self._col_shard[reg] = self.fleet.shard_of_task(req.task_id)
        return req

    def _service_for_peer(self, peer_id: str, task_id: str):
        shard = self.fleet.shard_of_peer(peer_id)
        if shard is None:
            shard = self.fleet.shard_of_task(task_id)
        return self.fleet.replicas[shard]

    # ------------------------------------------------------- ring events

    def _apply_host_churn(self) -> None:
        # ring maintenance rides the fault phase, before host churn: at
        # K=1 this is a no-op, so the base engine's round structure (and
        # the equivalence oracle) is untouched
        self._fleet_ring_step()
        super()._apply_host_churn()

    def _fleet_ring_step(self) -> None:
        fleet = self.fleet
        if fleet.k <= 1:
            return
        # rejoins first: a restarted replica re-enters the ring, then the
        # rebalance walk hands its tasks' in-flight peers back (adoption)
        for shard in sorted(self._down_until):
            if self._down_until[shard] <= self._round:
                del self._down_until[shard]
                fleet.shard_up(shard)
                self.timeline.mark_event(self._round, f"fleet_rejoin:{shard}")
                self._rebalance_handoffs("rebalance")
        if self.engine is None:
            return
        window = self.engine.upgrade_window(self._round)
        if window is None:
            return
        # rolling upgrade: replica k restarts when the host-order sweep
        # crosses its ring band's midpoint (k + 0.5)/K — a graceful
        # drain: handoff away, one round out, rebalance back on rejoin
        lo, hi = window
        wave_gap = max(self.engine.spec.upgrade.wave_rounds, 1)
        for shard in range(fleet.k):
            mid = (shard + 0.5) / fleet.k
            if not lo <= mid < hi:
                continue
            if self._round - self._upgrade_last[shard] < wave_gap:
                continue
            if shard in self._down_until or shard in fleet._down:
                continue
            self._upgrade_last[shard] = self._round
            self.timeline.mark_event(self._round, f"fleet_restart:{shard}")
            fleet.shard_down(shard)
            self._rebalance_handoffs("upgrade")
            self._down_until[shard] = self._round + 1

    def _rebalance_handoffs(self, reason: str) -> int:
        """Move every in-flight peer whose task's ring owner is no
        longer the replica holding it. Iteration is SORTED by peer id:
        the handoff order drives the receiving replica's pending-queue
        order (which maps candidate rows to children next tick), so set/
        dict iteration order must never leak into it — the exact
        determinism class the simulator's partition paths pin."""
        fleet = self.fleet
        moved = 0
        done_cap = self._col_done_round.shape[0]
        for pid in sorted(fleet._peer_shard):
            if not pid.startswith("peer-"):
                continue  # seed rows are serving state, not downloads
            task = self._task_of.get(pid)
            if task is None or pid in self._partition_stalled:
                continue  # retired, or waiting on a partition heal
            host_id = self._peer_host.get(pid)
            if (host_id is None or host_id in self._offline
                    or host_id in self._partitioned):
                continue  # its daemon cannot re-announce right now
            reg = self._reg_of(pid)
            if reg >= done_cap or self._col_done_round[reg] >= 0:
                continue  # completed — nothing in flight to move
            if fleet.shard_of_task(task["task_id"]) == fleet._peer_shard[pid]:
                continue
            self._handoff_peer(pid, task, reason)
            moved += 1
        return moved

    def _handoff_peer(self, pid: str, task: dict, reason: str) -> None:
        """Release one in-flight peer from its current shard and
        re-announce it to the task's ring owner, kept pieces riding the
        handoff frame for adoption. Goes through ``scheduler.handle`` so
        the mixed-version soak's skew proxy round-trips the frame."""
        fleet = self.fleet
        info = self._host_info.get(self._peer_host.get(pid))
        if info is None:
            return
        from_name = ""
        shard = fleet.shard_of_peer(pid)
        if shard is not None:
            from_name = fleet.names[shard]
        fleet.leave_peer(pid)
        self.scheduler.handle(msg.PeerHandoffRequest(
            peer_id=pid,
            task_id=task["task_id"],
            host=info,
            url=task["url"],
            content_length=task["content_length"],
            piece_length=self.piece_length,
            total_piece_count=task["pieces"],
            tag="sim",
            application="simulator",
            finished_pieces=self._finished_pieces(pid) or None,
            from_scheduler=from_name,
            reason=reason,
        ))
        reg = self._reg_of(pid)
        new_shard = fleet.shard_of_peer(pid)
        self._col_shard[reg] = -1 if new_shard is None else new_shard

    def _apply_scheduler_crash(self) -> None:
        """Fleet crash: ONE replica dies (round-robin victim — the
        deterministic stand-in for 'the unlucky process'), not the whole
        control plane. Victim-owned in-flight rows get the crash stamp
        (the base engine stamps every row — here only the victim's
        downloads lose their scheduler), its pending peers are released
        and handed off to the new ring owners with their kept pieces,
        and at K>1 the victim leaves the ring for ``crash_down_rounds``.
        At K=1 the sequence reduces exactly to the oracle's crash replay
        (leave stalled + pending, re-register with finished_pieces) —
        the handoff handler constructs the identical register request."""
        fleet = self.fleet
        victim = self._crash_counter % fleet.k
        self._crash_counter += 1
        self._crash_victims.append((self._round, victim))
        n = self._reg_index
        alive = (
            (self._col_task[:n] >= 0)
            & (self._col_done_round[:n] < 0)
            & (self._col_shard[:n] == victim)
        )
        self._col_crash_round[:n][alive] = self._round
        self._col_crash_cost_ns[:n][alive] = self._col_cost_ns[:n][alive]
        self.stats.injected_scheduler_crashes += 1
        self.timeline.mark_event(self._round, f"fleet_crash:{victim}")
        vsvc = fleet.replicas[victim]
        victims = [pid for pid in list(vsvc._pending) if pid in self._task_of]
        # sorted: _partition_stalled is a set of peer-id strings and the
        # leave order drives free-list and pending order (oracle contract)
        for pid in sorted(self._partition_stalled):
            if (pid in self._task_of and pid not in vsvc._pending
                    and fleet.shard_of_peer(pid) == victim):
                fleet.leave_peer(pid)
        for pid in victims:
            fleet.leave_peer(pid)
        if fleet.k > 1:
            fleet.shard_down(victim)
            self._down_until[victim] = self._round + self._crash_down_rounds
        for pid in victims:
            task = self._task_of[pid]
            info = self._host_info.get(self._peer_host.get(pid))
            if info is None:
                continue
            self.scheduler.handle(msg.PeerHandoffRequest(
                peer_id=pid,
                task_id=task["task_id"],
                host=info,
                url=task["url"],
                content_length=task["content_length"],
                piece_length=self.piece_length,
                total_piece_count=task["pieces"],
                tag="sim",
                application="simulator",
                finished_pieces=self._finished_pieces(pid) or None,
                from_scheduler=fleet.names[victim],
                reason="crash",
            ))
            new_shard = fleet.shard_of_peer(pid)
            self._col_shard[self._reg_of(pid)] = (
                -1 if new_shard is None else new_shard
            )
            self.stats.crash_reannounced_peers += 1
        if fleet.k > 1:
            # the victim's remaining in-flight peers (mid-download, not
            # pending) lost their scheduler too: their daemons re-dial
            # via the ring walk and land on the new owners. These are
            # scheduler-loss re-announces, so they burn the announce-
            # stability SLI with the pending victims — the kill round's
            # reannounce_backlog spike is what pages
            self.stats.crash_reannounced_peers += (
                self._rebalance_handoffs("crash")
            )

    # ------------------------------------------------------------- round

    def run_round(self, new_downloads: int = 8) -> list:
        responses = super().run_round(new_downloads)
        cur = self.fleet.sched_seconds()
        # per-shard scheduler-compute totals over the rounds (setup /
        # warmup excluded): replicas are independent machines in
        # production — no round barrier — so the fleet's critical path
        # is the BUSIEST shard's total, the makespan bound for
        # independent servers, not a per-round max
        for k, (c, p) in enumerate(zip(cur, self._sched_prev)):
            self._sched_round_s[k] += c - p
        self._sched_prev = cur
        return responses

    @property
    def _sched_serial_s(self) -> float:
        return sum(self._sched_round_s)

    @property
    def _sched_critical_s(self) -> float:
        return max(self._sched_round_s, default=0.0)

    def _timeline_sample(self, crashed: bool) -> None:
        super()._timeline_sample(crashed)
        fleet = self.fleet
        # TimelineRecorder.sample COPIES the values dict into the ring
        # entry — fleet columns mutate the entry in place, after the SLO
        # feed (they are fleet-plane attribution, not SLI inputs)
        entry = self.timeline.ring[-1]
        pieces = [int(v) for v in fleet.pieces_by_shard]
        entry["fleet_pieces"] = {
            name: pieces[shard] - self._tl_prev_shard[shard]
            for shard, name in enumerate(fleet.names)
        }
        self._tl_prev_shard = pieces
        handoffs = sum(fleet.handoffs.values())
        entry["fleet_handoffs"] = handoffs - self._tl_prev_handoffs
        self._tl_prev_handoffs = handoffs
        entry["shards_in_ring"] = len(fleet.ring)
        entry["shards_down"] = len(fleet.down_shards())

    def _observe_tail(self, reg: int) -> None:
        super()._observe_tail(reg)
        if not self.tail_capture or int(self._col_host[reg]) < 0:
            return
        shard = int(self._col_shard[reg])
        if shard < 0:
            return
        # the phase vector super() just built for this download; it sums
        # to the recorded TTC exactly (disjoint components)
        vec = self._tail_vec
        self.tail_shard.observe(
            shard, reg, float(vec.sum()), vec,
            round_idx=int(self._col_done_round[reg]),
        )

    # ---------------------------------------------------------- reporting

    def fleet_report(self) -> dict:
        """The deterministic ``fleet`` block for megascale reports:
        fleet-plane counters, per-shard entity counts and decision
        digests, the crash victim schedule with per-victim recovery
        measured on the victim shard's OWN piece-rate series, and the
        per-shard tail attribution."""
        from dragonfly2_tpu.telemetry.timeline import recovery_time

        fleet = self.fleet
        tl = self.timeline.timeline()
        shard_series: dict[str, list[dict]] = {
            name: [
                {"t": s["t"], "pieces": s["fleet_pieces"][name]}
                for s in tl if "fleet_pieces" in s
            ]
            for name in fleet.names
        }
        victim_recovery = []
        for r, shard in self._crash_victims:
            name = fleet.names[shard]
            victim_recovery.append({
                "round": int(r),
                "shard": name,
                **recovery_time(
                    shard_series[name], "pieces", r,
                    baseline_window=8, threshold=0.9,
                ),
            })
        return {
            "replicas": fleet.k,
            "names": list(fleet.names),
            **fleet.fleet_counters(),
            "counts_by_shard": fleet.counts_by_shard(),
            "decision_digests_by_shard": {
                name: (
                    replica.decisions.deterministic_digest()
                    if replica.decisions is not None else None
                )
                for name, replica in zip(fleet.names, fleet.replicas)
            },
            "crash_victims": [
                {"round": int(r), "shard": fleet.names[s]}
                for r, s in self._crash_victims
            ],
            "victim_recovery": victim_recovery,
            "tail_by_shard": self.tail_shard.report(
                crash_rounds=[r for r, _ in self._crash_victims]
            ),
        }

    def fleet_timing(self, wall_s: float) -> dict:
        """Wall-derived (NON-deterministic — rides the report's `timing`
        block only) fleet throughput accounting. The in-process replay
        runs K replicas serially on one core; in production each replica
        is its own machine with no round barrier, so:

        - ``sched_serial_s``: summed per-shard scheduler-compute seconds
          — what this replay actually paid for the control plane;
        - ``sched_critical_s``: the BUSIEST shard's total — the makespan
          bound for K independent servers (at K=1 the two are equal);
        - ``modeled_parallel_wall_s``: this replay's wall with the
          serial scheduler time replaced by the critical path;
        - ``aggregate_pieces_per_sec``: pieces over the critical path —
          the control-plane capacity of the fleet. The event-batch
          engine's own numpy time prices the DATA plane (a million
          client machines in production, not scheduler compute), so it
          stays out of this cell; it still dominates
          ``modeled_parallel_wall_s`` for the replay-speed view.

        The 1-vs-K scaling artifact compares ``aggregate_pieces_per_sec``
        across replica counts."""
        modeled = max(
            wall_s - self._sched_serial_s + self._sched_critical_s, 1e-9
        )
        return {
            "sched_serial_s": round(self._sched_serial_s, 2),
            "sched_critical_s": round(self._sched_critical_s, 2),
            "sched_seconds_by_shard": {
                name: round(s, 2)
                for name, s in zip(self.fleet.names, self._sched_round_s)
            },
            "modeled_parallel_wall_s": round(modeled, 2),
            "aggregate_pieces_per_sec": round(
                self.stats.pieces / max(self._sched_critical_s, 1e-9), 1
            ),
        }


def megascale_fleet(
    num_hosts: int,
    num_tasks: int = 64,
    max_live_peers: int | None = None,
    algorithm: str = "default",
    seed: int = 0,
    max_peers_per_task: int = 2048,
    replicas: int = 1,
) -> SchedulerFleet:
    """A SchedulerFleet sized for a megascale run. K=1 builds the exact
    ``megascale_service`` configuration (bit-identical Config + seed —
    the equivalence oracle's precondition). K>1 seeds replica k with
    ``seed + k`` and sizes each peer table to its ring share with 1.5x
    slack for ring-cut jitter and crash-handoff bursts; task/host tables
    stay full-size (a hot task lives WHOLE on one shard, and every
    replica announces every host)."""
    k = max(int(replicas), 1)
    if k == 1:
        services = [megascale_service(
            num_hosts, num_tasks=num_tasks, max_live_peers=max_live_peers,
            algorithm=algorithm, seed=seed,
            max_peers_per_task=max_peers_per_task,
        )]
    else:
        live = max_live_peers or max(4 * num_hosts, 4096)
        # 1.5x slack over an even 1/K cut: the 256-vnode ring keeps each
        # replica's band within a few percent of 1/K, and a crashed
        # replica's band redistributes to the survivors at ~1.33/K peak
        # — oversizing beyond that only inflates every replica's
        # fixed per-tick sweep cost, which is pure serial overhead the
        # 1-vs-K scaling cell then charges to the fleet
        per_shard = -(-(live * 3) // (2 * k))
        services = [
            megascale_service(
                num_hosts, num_tasks=num_tasks, max_live_peers=per_shard,
                algorithm=algorithm, seed=seed + shard,
                max_peers_per_task=max_peers_per_task,
            )
            for shard in range(k)
        ]
    # megascale catalogs are a few hundred tasks over a handful of
    # replicas: 256 vnodes per replica keeps each shard's cut of the
    # catalog near 1/K (the 64-vnode default leaves ~±30% band lumps,
    # which at 10^6 hosts turns one replica into the fleet's critical
    # path before popularity skew even enters)
    return SchedulerFleet(services, vnodes=256)
