from dragonfly2_tpu.telemetry.metrics import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    serve_metrics,
)
from dragonfly2_tpu.telemetry.tracing import Span, Tracer, default_tracer  # noqa: F401
