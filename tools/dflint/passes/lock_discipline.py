"""LOCK001 — mixed guarded/unguarded mutation of a class attribute.

For every class that owns at least one ``threading.Lock``/``RLock``/
``Condition`` attribute, the pass infers which ``self.*`` attributes are
mutated inside ``with self.<lock>:`` scopes. An attribute that is
mutated under a lock in one place and bare in another is exactly the
shape of bug Go's race detector catches at runtime: the class clearly
TREATS the attribute as shared, but at least one writer skips the lock.
Attributes that are never guarded anywhere are not flagged — plenty of
classes are single-threaded by design, and the mixed pattern is the
signal.

Cross-method lock knowledge travels two ways:

- ``# dflint: under[<lock>]`` on a ``def`` line asserts "every caller
  holds ``self.<lock>``" — the body is analyzed with that lock held.
  The runtime lock-order harness is the dynamic check of the marker.
- Call-graph propagation: a private method whose every in-class call
  site sits inside ``with self.<lock>:`` (or inside a method itself
  entered with the lock) inherits the lock, so internal helpers do not
  need markers when the code already proves the discipline.

Mutations counted: assignment / augmented assignment / ``del`` whose
target chain roots at ``self.<attr>``, and calls of known mutating
methods (``append``, ``add``, ``pop``, ``update``, …) on such chains.
Reads are deliberately NOT counted — lock-free reads of
atomically-swapped references are an idiom this codebase uses on
purpose (``_EmbSnapshot``, buffered-report truthiness probes).

Known approximation: a nested function inherits the with-stack at its
definition site. Closures defined inside a lock scope and *called* there
(the tick's ``_dispatch_chunk``/``_drain_chunk``) analyze correctly; a
closure that escapes the scope would be mis-credited — none do today.
"""

from __future__ import annotations

import ast
import dataclasses

from tools.dflint.core import FileContext, Finding, attr_chain

LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

MUTATOR_METHODS = {
    "append", "appendleft", "extend", "insert", "remove", "pop", "popleft",
    "popitem", "clear", "add", "discard", "update", "setdefault", "sort",
    "reverse", "rotate", "__setitem__", "insort",
}

INIT_METHODS = {"__init__", "__post_init__", "__new__"}


@dataclasses.dataclass
class _Site:
    attr: str
    node: ast.AST
    method: str
    def_line: int
    held: frozenset[str]


@dataclasses.dataclass
class _CallSite:
    callee: str
    held: frozenset[str]
    caller: str


class LockDisciplinePass:
    name = "lock-discipline"
    rules = ("LOCK001",)

    def run(self, ctx: FileContext) -> list[Finding]:
        findings: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(ctx, node))
        return findings

    # ------------------------------------------------------------ class

    def _check_class(self, ctx: FileContext, cls: ast.ClassDef) -> list[Finding]:
        locks = _collect_lock_attrs(cls)
        if not locks:
            return []
        methods = [
            stmt for stmt in cls.body
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        sites: list[_Site] = []
        calls: list[_CallSite] = []
        markers: dict[str, frozenset[str]] = {}
        for func in methods:
            under = ctx.under_lock(func)
            if under is not None:
                markers[func.name] = frozenset({under})
            if func.name in INIT_METHODS:
                continue  # construction precedes sharing
            collector = _MethodCollector(func.name, func.lineno, locks)
            for stmt in func.body:
                collector.visit(stmt)
            sites.extend(collector.sites)
            calls.extend(collector.calls)

        entry = _propagate_entry_locks(
            [f.name for f in methods], markers, calls, locks
        )

        guarded: dict[str, list[_Site]] = {}
        bare: dict[str, list[_Site]] = {}
        for site in sites:
            effective = site.held | entry.get(site.method, frozenset())
            bucket = guarded if effective & locks else bare
            bucket.setdefault(site.attr, []).append(site)

        findings = []
        for attr, bare_sites in sorted(bare.items()):
            guarded_sites = guarded.get(attr)
            if not guarded_sites:
                continue  # never guarded anywhere: single-threaded idiom
            lock_names = sorted(
                set().union(*[
                    s.held | entry.get(s.method, frozenset())
                    for s in guarded_sites
                ]) & locks
            )
            example = guarded_sites[0]
            for site in bare_sites:
                findings.append(ctx.make_finding(
                    "LOCK001",
                    site.node,
                    (
                        f"self.{attr} is mutated under "
                        f"{'/'.join('self.' + ln for ln in lock_names)} "
                        f"elsewhere in {cls.name} "
                        f"(e.g. {example.method}:{example.node.lineno}) but "
                        f"bare here — either take the lock, mark the method "
                        f"'# dflint: under[{lock_names[0]}]', or waive with "
                        f"a justification"
                    ),
                    symbol=f"{cls.name}.{site.method}",
                    def_line=site.def_line,
                ))
        return findings


# ----------------------------------------------------------------- helpers


def _collect_lock_attrs(cls: ast.ClassDef) -> frozenset[str]:
    """Attributes assigned a threading.Lock/RLock/Condition anywhere in
    the class body (typically ``__init__``)."""
    locks: set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        callee = attr_chain(value.func)
        if callee is None:
            continue
        leaf = callee.rsplit(".", 1)[-1]
        if leaf not in LOCK_FACTORIES:
            continue
        for target in node.targets:
            chain = attr_chain(target)
            if chain is not None and chain.startswith("self.") and chain.count(".") == 1:
                locks.add(chain.split(".", 1)[1])
    return frozenset(locks)


def _propagate_entry_locks(
    method_names: list[str],
    markers: dict[str, frozenset[str]],
    calls: list[_CallSite],
    locks: frozenset[str],
) -> dict[str, frozenset[str]]:
    """Fixpoint: which locks are guaranteed held at entry of each method.

    Public methods (no leading underscore) are externally callable bare:
    entry = their marker (or nothing). Private methods start optimistic
    (all locks) and intersect over every in-class call site's
    held-at-site ∪ caller-entry; a private method nobody in the class
    calls gets the empty set (unknown callers — likely called via a
    dispatch table or externally)."""
    call_sites: dict[str, list[_CallSite]] = {}
    for call in calls:
        call_sites.setdefault(call.callee, []).append(call)

    entry: dict[str, frozenset[str]] = {}
    for name in method_names:
        if name in markers:
            entry[name] = markers[name]
        elif name.startswith("_") and not name.startswith("__") and call_sites.get(name):
            entry[name] = locks  # optimistic start; intersected below
        else:
            entry[name] = frozenset()

    for _ in range(len(method_names) + 1):
        changed = False
        for name in method_names:
            if name in markers or not (
                name.startswith("_") and not name.startswith("__")
            ):
                continue
            sites = call_sites.get(name)
            if not sites:
                continue
            new = frozenset(locks)
            for site in sites:
                new &= site.held | entry.get(site.caller, frozenset())
            if new != entry[name]:
                entry[name] = new
                changed = True
        if not changed:
            break
    return entry


class _MethodCollector(ast.NodeVisitor):
    """Walk one method body tracking the ``with self.<lock>:`` stack;
    record mutation sites and in-class call sites with the held set."""

    def __init__(self, method: str, def_line: int, locks: frozenset[str]):
        self.method = method
        self.def_line = def_line
        self.locks = locks
        self.held: list[str] = []
        self.sites: list[_Site] = []
        self.calls: list[_CallSite] = []

    # ------------------------------------------------------ with scopes

    def visit_With(self, node: ast.With) -> None:
        acquired = []
        for item in node.items:
            chain = attr_chain(item.context_expr)
            if chain is not None and chain.startswith("self."):
                name = chain.split(".", 1)[1]
                if name in self.locks:
                    acquired.append(name)
        self.held.extend(acquired)
        for stmt in node.body:
            self.visit(stmt)
        for item in node.items:
            self.visit(item.context_expr)
        if acquired:
            del self.held[len(self.held) - len(acquired):]

    visit_AsyncWith = visit_With  # same scoping rules

    # ------------------------------------------------------- mutations

    def _record_target(self, target: ast.AST) -> None:
        chain = attr_chain(target)
        if chain is None:
            # self.x[k] = v / self.x.y[k] = v — unwrap subscripts
            while isinstance(target, ast.Subscript):
                target = target.value
            chain = attr_chain(target)
        if chain is None or not chain.startswith("self."):
            return
        attr = chain.split(".")[1]
        if attr in self.locks:
            return  # re-binding the lock itself is its own (rare) sin
        self.sites.append(_Site(
            attr, target, self.method, self.def_line, frozenset(self.held)
        ))

    def visit_Assign(self, node: ast.Assign) -> None:
        for target in node.targets:
            if isinstance(target, (ast.Tuple, ast.List)):
                for elt in target.elts:
                    self._record_target(elt)
            else:
                self._record_target(target)
        self.visit(node.value)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node.target)
        self.visit(node.value)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node.target)
            self.visit(node.value)

    def visit_Delete(self, node: ast.Delete) -> None:
        for target in node.targets:
            self._record_target(target)

    def visit_Call(self, node: ast.Call) -> None:
        chain = attr_chain(node.func)
        if chain is not None and chain.startswith("self."):
            parts = chain.split(".")
            if len(parts) >= 3 and parts[-1] in MUTATOR_METHODS:
                # self.<attr>(...).append-style chains root at the attr
                self.sites.append(_Site(
                    parts[1], node, self.method, self.def_line,
                    frozenset(self.held),
                ))
            elif len(parts) == 2:
                self.calls.append(_CallSite(
                    parts[1], frozenset(self.held), self.method
                ))
        self.generic_visit(node)

    # nested defs inherit the with-stack at their definition site (see
    # module docstring for the escape caveat)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        for stmt in node.body:
            self.visit(stmt)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self.visit(node.body)
